"""NLP stack tests.

Models the reference's NLP test strategy (SURVEY.md §4: tokenizer/iterator
unit tests + small-corpus Word2Vec similarity-sanity tests —
Word2VecTestsSmall.java, VocabConstructorTest.java).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    AbstractCache, BasicLineIterator, CollectionSentenceIterator,
    CommonPreprocessor, DefaultTokenizerFactory, Glove, LabelAwareIterator,
    LabelledDocument, NGramTokenizerFactory, ParagraphVectors,
    SequenceVectors, VocabConstructor, VocabWord, Word2Vec,
    WordVectorSerializer, build_huffman_tree)


def _toy_corpus(n_rep=40):
    """Structured corpus: 'day'/'night' share contexts, 'cat'/'dog' share
    contexts, the two clusters never mix."""
    a = ["the day was bright and the night was dark",
         "every day follows a night and every night follows a day",
         "day and night alternate like light and dark"]
    b = ["the cat chased the dog around the yard",
         "a dog barked while the cat slept on the mat",
         "cat and dog play together in the yard"]
    return (a + b) * n_rep


# -- tokenization -----------------------------------------------------------

def test_default_tokenizer_and_preprocessor():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    toks = tf.create("Hello, World! 123 foo-bar").get_tokens()
    assert "hello" in toks and "world" in toks
    assert all("," not in t and "!" not in t for t in toks)


def test_ngram_tokenizer():
    tf = NGramTokenizerFactory(1, 2)
    toks = tf.create("a b c").get_tokens()
    assert "a" in toks and "a b" in toks and "b c" in toks


# -- vocab ------------------------------------------------------------------

def test_vocab_constructor_counts_and_min_frequency():
    seqs = [["a", "b", "a"], ["a", "c"]]
    cache = VocabConstructor(min_word_frequency=2).build_vocab(seqs)
    assert cache.contains_word("a")
    assert not cache.contains_word("b")  # freq 1 < 2
    assert cache.word_frequency("a") == 3
    assert cache.index_of("a") == 0  # most frequent first


def test_huffman_codes_prefix_free():
    cache = AbstractCache()
    for w, f in [("a", 10), ("b", 5), ("c", 3), ("d", 1)]:
        cache.add_token(VocabWord(w, f))
    cache.finalize_vocab()
    build_huffman_tree(cache)
    codes = {w.word: "".join(map(str, w.code))
             for w in cache.vocab_words()}
    # prefix-free property
    vals = list(codes.values())
    for i, c1 in enumerate(vals):
        for j, c2 in enumerate(vals):
            if i != j:
                assert not c2.startswith(c1)
    # more frequent words get shorter codes
    assert len(codes["a"]) <= len(codes["d"])


# -- iterators --------------------------------------------------------------

def test_basic_line_iterator(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("line one\nline two\nline three\n")
    it = BasicLineIterator(str(p))
    assert list(it) == ["line one", "line two", "line three"]
    it.reset()
    assert it.next_sentence() == "line one"


# -- word2vec ---------------------------------------------------------------

def test_word2vec_similarity_sanity():
    """Reference analog: Word2VecTestsSmall — related words end up closer
    than unrelated ones."""
    w2v = Word2Vec(sentences=_toy_corpus(), layer_size=32, window=3,
                   negative=5, epochs=3, seed=42, learning_rate=0.05,
                   min_word_frequency=3, batch_size=256)
    w2v.fit()
    assert w2v.has_word("day") and w2v.has_word("cat")
    related = w2v.similarity("day", "night")
    cross = w2v.similarity("day", "dog")
    assert related > cross, (related, cross)
    nearest = w2v.words_nearest("day", top_n=5)
    assert "night" in nearest


def test_word2vec_builder_api():
    it = CollectionSentenceIterator(_toy_corpus(5))
    w2v = (Word2Vec.builder().iterate(it).layer_size(16).window_size(2)
           .min_word_frequency(1).learning_rate(0.05).negative_sample(3)
           .epochs(1).seed(7).batch_size(128).build())
    w2v.fit()
    assert w2v.word_vector("day").shape == (16,)


def test_word2vec_hierarchical_softmax():
    w2v = Word2Vec(sentences=_toy_corpus(10), layer_size=16, window=3,
                   negative=0, use_hierarchic_softmax=True, epochs=2,
                   seed=3, min_word_frequency=2, batch_size=128)
    w2v.fit()
    v = w2v.word_vector("day")
    assert v is not None and np.isfinite(v).all()
    assert not np.allclose(v, 0)


def test_word2vec_cbow():
    w2v = Word2Vec(sentences=_toy_corpus(10), layer_size=16, window=3,
                   negative=3, epochs=2, seed=3, min_word_frequency=2,
                   batch_size=128, elements_learning_algorithm="cbow")
    w2v.fit()
    assert np.isfinite(w2v.word_vector("night")).all()


# -- serialization ----------------------------------------------------------

@pytest.fixture(scope="module")
def small_model():
    w2v = Word2Vec(sentences=_toy_corpus(10), layer_size=12, window=2,
                   negative=3, epochs=1, seed=11, min_word_frequency=2,
                   batch_size=128)
    w2v.fit()
    return w2v


def test_txt_roundtrip(small_model, tmp_path):
    p = str(tmp_path / "vectors.txt")
    WordVectorSerializer.write_word_vectors(small_model, p)
    loaded = WordVectorSerializer.load_txt_vectors(p)
    for w in ("day", "night", "cat"):
        np.testing.assert_allclose(loaded.word_vector(w),
                                   small_model.word_vector(w), atol=1e-5)


def test_binary_roundtrip(small_model, tmp_path):
    p = str(tmp_path / "vectors.bin")
    WordVectorSerializer.write_binary(small_model, p)
    loaded = WordVectorSerializer.read_binary_model(p)
    for w in ("day", "dog"):
        np.testing.assert_allclose(loaded.word_vector(w),
                                   small_model.word_vector(w), atol=1e-6)


def test_full_model_roundtrip_resumes_training(small_model, tmp_path):
    p = str(tmp_path / "full.npz")
    WordVectorSerializer.write_full_model(small_model, p)
    loaded = WordVectorSerializer.load_full_model(p)
    np.testing.assert_allclose(loaded.word_vector("day"),
                               small_model.word_vector("day"), atol=1e-6)
    # resume training: attach a corpus and run another epoch
    loaded.sentence_iterator = CollectionSentenceIterator(_toy_corpus(2))
    before = loaded.word_vector("day").copy()
    loaded.fit()
    after = loaded.word_vector("day")
    assert not np.allclose(before, after)  # weights moved


# -- paragraph vectors ------------------------------------------------------

def test_paragraph_vectors_doc_similarity():
    docs = []
    for i in range(6):
        docs.append(LabelledDocument(
            "the day was bright and the night was dark and day follows "
            "night", [f"SKY_{i}"]))
        docs.append(LabelledDocument(
            "the cat chased the dog and the dog chased the cat in the "
            "yard", [f"PET_{i}"]))
    pv = ParagraphVectors(iterator=LabelAwareIterator(docs), layer_size=24,
                          window=3, negative=4, epochs=12, seed=5,
                          min_word_frequency=1, batch_size=128,
                          learning_rate=0.05,
                          sequence_learning_algorithm="dm")
    pv.fit()
    same = pv.doc_similarity("SKY_0", "SKY_1")
    diff = pv.doc_similarity("SKY_0", "PET_0")
    assert same > diff, (same, diff)
    vec = pv.infer_vector("day and night and day")
    assert vec.shape == (24,) and np.isfinite(vec).all()


def test_paragraph_vectors_dbow():
    docs = [LabelledDocument("day night day night bright dark", ["A"]),
            LabelledDocument("cat dog cat dog yard mat", ["B"])]
    pv = ParagraphVectors(iterator=LabelAwareIterator(docs), layer_size=8,
                          window=2, negative=3, epochs=5, seed=5,
                          min_word_frequency=1, batch_size=64,
                          sequence_learning_algorithm="dbow")
    pv.fit()
    assert pv.doc_vector("A").shape == (8,)
    assert np.isfinite(pv.doc_vector("A")).all()


# -- glove ------------------------------------------------------------------

def test_glove_trains_and_queries():
    g = Glove(sentences=_toy_corpus(20), layer_size=16, window=4, epochs=8,
              learning_rate=0.05, min_word_frequency=2, seed=1,
              batch_size=256)
    g.fit()
    related = g.similarity("day", "night")
    cross = g.similarity("day", "dog")
    assert np.isfinite(related) and np.isfinite(cross)
    assert related > cross, (related, cross)


def test_vectorizers_and_inverted_index():
    from deeplearning4j_tpu.nlp.vectorizers import (BagOfWordsVectorizer,
                                                    TfidfVectorizer)
    docs = ["the cat sat on the mat", "the dog sat on the log",
            "cats and dogs"]
    bow = BagOfWordsVectorizer()
    m = np.asarray(bow.fit_transform(docs))
    assert m.shape[0] == 3
    the_idx = bow.vocab.index_of("the")
    assert m[0, the_idx] == 2  # 'the' twice in doc 0
    assert bow.index.documents("sat") == [0, 1]
    assert bow.index.num_documents() == 3

    tf = TfidfVectorizer()
    t = np.asarray(tf.fit_transform(docs))
    cat_idx = tf.vocab.index_of("cat")
    # 'cat' (1 doc) outweighs 'sat' (2 docs) per-occurrence in doc 0
    sat_idx = tf.vocab.index_of("sat")
    assert t[0, cat_idx] > t[0, sat_idx]


@pytest.mark.parametrize("mode", ["sg-neg", "sg-hs", "cbow-neg",
                                  "cbow-hs"])
def test_scanned_word2vec_matches_per_batch(mode):
    """The whole-epoch scanned programs (_fit_epoch_scanned) must
    reproduce the per-batch dispatch path exactly for every algorithm
    mode — same RNG stream, same lr schedule, lr=0 padding no-ops (the
    proof obligation every scanned path in the repo carries, cf.
    fit_batched tests)."""
    kw = dict(sentences=_toy_corpus(10), layer_size=16, window=3,
              epochs=2, seed=13, min_word_frequency=2,
              batch_size=64, learning_rate=0.05)
    if mode == "sg-neg":
        kw.update(negative=3)
    elif mode == "sg-hs":
        kw.update(negative=0, use_hierarchic_softmax=True)
    elif mode == "cbow-neg":
        kw.update(negative=3, elements_learning_algorithm="cbow")
    else:
        kw.update(negative=0, use_hierarchic_softmax=True,
                  elements_learning_algorithm="cbow")
    scanned = Word2Vec(**kw)
    scanned.fit()
    stepped = Word2Vec(scan_epochs=False, **kw)
    stepped.fit()
    np.testing.assert_allclose(
        np.asarray(scanned.lookup_table.syn0),
        np.asarray(stepped.lookup_table.syn0), rtol=0, atol=1e-7)


def test_distributed_glove_matches_single(devices8):
    """Mesh-sharded GloVe == single-device GloVe (the spark-nlp
    GlovePerformer analog, same spark-vs-single proof pattern)."""
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    kw = dict(sentences=_toy_corpus(8), layer_size=16, window=3,
              epochs=3, seed=13, min_word_frequency=2, batch_size=64,
              learning_rate=0.05)
    single = Glove(**kw)
    single.fit()
    dist = Glove(mesh=data_parallel_mesh(8), **kw)
    dist.fit()
    np.testing.assert_allclose(
        np.asarray(single.lookup_table.syn0),
        np.asarray(dist.lookup_table.syn0), rtol=1e-4, atol=1e-5)


def test_distributed_word2vec_matches_single(devices8):
    """Mesh-sharded skip-gram must track the single-device trainer
    (the reference's spark-vs-single equivalence pattern, SURVEY §4)."""
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    kw = dict(sentences=_toy_corpus(10), layer_size=16, window=3,
              negative=3, epochs=2, seed=13, min_word_frequency=2,
              batch_size=64, learning_rate=0.05)
    single = Word2Vec(**kw)
    single.fit()
    dist = Word2Vec(mesh=data_parallel_mesh(8), **kw)
    dist.fit()
    v1 = single.word_vector("day")
    v2 = dist.word_vector("day")
    np.testing.assert_allclose(v1, v2, rtol=1e-4, atol=1e-5)
    assert dist.similarity("day", "night") > dist.similarity("day", "dog")


def test_cjk_and_regex_tokenizers():
    from deeplearning4j_tpu.nlp.tokenization import (CJKTokenizerFactory,
                                                     RegexTokenizerFactory)
    toks = CJKTokenizerFactory(2).create("私は猫が好き hello").get_tokens()
    assert "私は" in toks and "hello" in toks
    assert all(len(t) == 2 or t.isascii() for t in toks)
    r = RegexTokenizerFactory(r"[a-z]+").create("foo BAR baz").get_tokens()
    assert r == ["foo", "baz"]


def test_nan_guard_listener():
    from deeplearning4j_tpu.train.listeners import NanScoreGuardListener
    import pytest as _pytest
    g = NanScoreGuardListener()
    g.iteration_done(None, 1, 0.5)  # fine
    with _pytest.raises(FloatingPointError):
        g.iteration_done(None, 2, float("nan"))
    soft = NanScoreGuardListener(raise_on_invalid=False)
    soft.iteration_done(None, 3, float("inf"))
    assert soft.tripped_at == 3


def test_stream_line_iterator_and_vocabulary_holder():
    """Reference analogs: sentenceiterator/StreamLineIterator.java,
    wordstore/VocabularyHolder.java."""
    import io
    from deeplearning4j_tpu.nlp import (AbstractCache, StreamLineIterator,
                                        VocabularyHolder)
    it = StreamLineIterator(io.StringIO("a b c\nd e\n"))
    assert list(it) == ["a b c", "d e"]
    assert list(it) == ["a b c", "d e"]  # reset works

    holder = VocabularyHolder(min_word_frequency=2)
    for w in ["the", "the", "the", "cat", "cat", "rare"]:
        holder.add_word(w)
    assert holder.word_frequency("the") == 3
    holder.truncate_vocabulary()
    assert holder.num_words() == 2  # 'rare' dropped
    cache = holder.transfer_back_to_vocab_cache(AbstractCache())
    assert cache.contains_word("the") and not cache.contains_word("rare")
    assert cache.word_for("the").index == 0  # most frequent first
    assert cache.word_for("the").code  # Huffman built


def test_pos_tagging_and_filtered_tokenizer():
    """POS tagging + allow-list filtering (reference capability:
    deeplearning4j-nlp-uima PosUimaTokenizer allowedPosTags)."""
    from deeplearning4j_tpu.nlp.pos import (PosTaggedTokenizerFactory,
                                            pos_tag)
    from deeplearning4j_tpu.nlp.tokenization import \
        DefaultTokenizerFactory

    tags = dict(pos_tag("the quick dogs ran quickly to 42 rivers".split()))
    assert tags["the"] == "DT"
    assert tags["dogs"] == "NNS"
    assert tags["quickly"] == "RB"
    assert tags["to"] == "TO"
    assert tags["42"] == "CD"
    # mid-sentence capitalization → proper noun
    assert dict(pos_tag("visit London today".split()))["London"] == "NNP"

    # noun-only stream, PosUimaTokenizer-style
    fac = PosTaggedTokenizerFactory(DefaultTokenizerFactory(),
                                    allowed_pos_tags=["NN", "NNS"])
    toks = fac.create("the quick movement of dogs ran to the station"
                      ).get_tokens()
    assert "movement" in toks and "dogs" in toks and "station" in toks
    assert "the" not in toks and "of" not in toks and "ran" not in toks


def test_cnn_sentence_dataset_iterator():
    """CnnSentenceDataSetIterator parity (reference:
    iterator/CnnSentenceDataSetIterator.java) — text-CNN pipeline from
    trained word vectors through a Convolution1D classifier."""
    from deeplearning4j_tpu.nlp import (CollectionSentenceIterator,
                                        Word2Vec)
    from deeplearning4j_tpu.nlp.cnn_sentence import (
        CnnSentenceDataSetIterator, CollectionLabeledSentenceProvider)

    pos = ["good great fine nice", "great nice good happy",
           "fine happy great good"] * 6
    neg = ["bad awful poor sad", "awful sad bad gloomy",
           "poor gloomy awful bad"] * 6
    sents = pos + neg
    labels = ["pos"] * len(pos) + ["neg"] * len(neg)
    w2v = Word2Vec(sentence_iterator=CollectionSentenceIterator(sents),
                   layer_size=12, min_word_frequency=1, epochs=3, seed=1)
    w2v.fit()

    provider = CollectionLabeledSentenceProvider(sents, labels)
    it = CnnSentenceDataSetIterator(provider, w2v, batch_size=12,
                                    max_sentence_length=6)
    assert it.get_labels() == ["neg", "pos"]
    b = next(iter(it))
    assert b.features.shape == (12, 6, 12, 1)
    assert b.labels.shape == (12, 2)
    assert b.features_mask.shape == (12, 6)
    assert b.features_mask[0].sum() == 4  # 4 known tokens
    single = it.load_single_sentence("good bad")
    assert single.shape == (1, 6, 12, 1)
    # padding rows are zero
    assert float(np.abs(single[0, 2:]).max()) == 0.0

    # unknown handling: zero keeps position with zero vector
    it_zero = CnnSentenceDataSetIterator(
        provider, w2v, batch_size=4, max_sentence_length=6,
        unknown_word_handling="zero")
    s = it_zero.load_single_sentence("good UNKNOWNWORD bad")
    assert float(np.abs(s[0, 1]).max()) == 0.0  # zero slot kept
    assert float(np.abs(s[0, 2]).max()) > 0.0   # 'bad' after it


def test_aggregating_sentence_iterator():
    from deeplearning4j_tpu.nlp import CollectionSentenceIterator
    from deeplearning4j_tpu.nlp.sentenceiterator import \
        AggregatingSentenceIterator
    a = CollectionSentenceIterator(["one", "two"])
    b = CollectionSentenceIterator(["three"])
    agg = AggregatingSentenceIterator(a, b)
    assert list(agg) == ["one", "two", "three"]
    assert list(agg) == ["one", "two", "three"]  # reset works


def test_cnn_sentence_orientation_and_oov_mask():
    from deeplearning4j_tpu.nlp import (CnnSentenceDataSetIterator,
                                        CollectionLabeledSentenceProvider,
                                        CollectionSentenceIterator,
                                        Word2Vec)
    sents = ["alpha beta", "beta alpha", "zzz qqq"]  # last is all-OOV
    w2v = Word2Vec(sentence_iterator=CollectionSentenceIterator(
        ["alpha beta"] * 10), layer_size=8, min_word_frequency=1,
        epochs=1, seed=1)
    w2v.fit()
    provider = CollectionLabeledSentenceProvider(sents, ["a", "b", "a"])
    it = CnnSentenceDataSetIterator(provider, w2v, batch_size=3,
                                    max_sentence_length=5,
                                    sentences_along_height=False)
    b = next(iter(it))
    assert b.features.shape == (3, 8, 5, 1)  # [B, D, T, 1] transposed
    # all-OOV row keeps one masked step (no zero-sum masks)
    assert b.features_mask[2].sum() == 1
    assert b.features_mask.min(axis=1).sum() == 0


def test_text_cnn_zoo_builder_with_sentence_iterator():
    """models/zoo.text_cnn + CnnSentenceDataSetIterator end to end."""
    from deeplearning4j_tpu import MultiLayerNetwork
    from deeplearning4j_tpu.models.zoo import text_cnn
    from deeplearning4j_tpu.nlp import (CnnSentenceDataSetIterator,
                                        CollectionLabeledSentenceProvider,
                                        CollectionSentenceIterator,
                                        Word2Vec)
    pos = ["good great fine nice"] * 8
    neg = ["bad awful poor sad"] * 8
    sents, labels = pos + neg, ["p"] * 8 + ["n"] * 8
    w2v = Word2Vec(sentence_iterator=CollectionSentenceIterator(sents),
                   layer_size=12, min_word_frequency=1, epochs=2, seed=2)
    w2v.fit()
    it = CnnSentenceDataSetIterator(
        CollectionLabeledSentenceProvider(sents, labels), w2v,
        batch_size=16, max_sentence_length=5)
    net = MultiLayerNetwork(text_cnn(embedding_dim=12, num_classes=2,
                                     learning_rate=0.01)).init()
    for _ in range(25):
        for b in it:
            net.fit(b.features[..., 0], b.labels)
    b = next(iter(it))
    preds = np.asarray(net.output(b.features[..., 0])).argmax(1)
    assert (preds == b.labels.argmax(1)).mean() > 0.9


def test_pos_uima_factory_parity():
    """Reference parity: PosUimaTokenizerFactoryTest.testCreate1/2 —
    allowed ["NN"] on "some test string" gives ["NONE","test","string"]
    and, with strip_nones, ["test","string"]."""
    from deeplearning4j_tpu.nlp.pos import PosTaggedTokenizerFactory
    from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory

    f = PosTaggedTokenizerFactory(DefaultTokenizerFactory(), ["NN"])
    assert f.create("some test string").get_tokens() == \
        ["NONE", "test", "string"]
    f2 = PosTaggedTokenizerFactory(DefaultTokenizerFactory(), ["NN"],
                                   strip_nones=True)
    assert f2.create("some test string").get_tokens() == ["test", "string"]


def test_stemming_preprocessor_parity():
    """Reference parity: StemmingPreprocessorTest —
    preProcess("TESTING.") == "test"."""
    from deeplearning4j_tpu.nlp.tokenization import StemmingPreprocessor

    p = StemmingPreprocessor()
    assert p.pre_process("TESTING.") == "test"
    assert p.pre_process("classes") == "class"
    assert p.pre_process("dogs") == "dog"
    assert p.pre_process("Jumped!") == "jump"


def test_segmenting_sentence_iterator():
    """UimaSentenceIterator capability analog: multi-sentence documents
    split at terminators, abbreviation-safe."""
    from deeplearning4j_tpu.nlp.sentenceiterator import \
        SegmentingSentenceIterator

    doc = ("Dr. Smith went to Washington. He arrived at 3.30 p.m? "
           "No one noticed! It was e.g. a quiet day.")
    sents = SegmentingSentenceIterator.segment(doc)
    assert sents[0] == "Dr. Smith went to Washington."
    assert any(s.startswith("No one noticed") for s in sents)
    it = SegmentingSentenceIterator([doc, "Single sentence here."])
    all_s = list(it)
    assert "Single sentence here." in all_s
    assert len(all_s) >= 4


def test_word2vec_subsampling_path():
    """subsampling > 0 exercises _freq_arr/_subsampled_corpus (r5: a
    num_words-as-method bug crashed this path — zero coverage before);
    frequent words must be dropped from the training stream and the
    model still trains."""
    w = Word2Vec(sentences=_toy_corpus(10), layer_size=16, window=3,
                 epochs=1, seed=13, min_word_frequency=1, batch_size=64,
                 subsampling=1e-3, negative=3)
    w.build_vocab()
    flat_all, _ = w._encoded_corpus()
    flat_sub, _sid = w._subsampled_corpus()
    assert 0 < len(flat_sub) < len(flat_all)
    w.fit()
    assert np.isfinite(np.asarray(w.lookup_table.syn0)).all()


def test_tokenizer_fast_path_matches_protocol():
    """The no-preprocessor get_tokens fast path must keep the protocol
    loop's semantics: empty tokens filtered, stream consumed."""
    from deeplearning4j_tpu.nlp.tokenization import Tokenizer
    t = Tokenizer(["a", "", "b", "", "c"], None)
    assert t.get_tokens() == ["a", "b", "c"]
    assert t.get_tokens() == []          # consumed
    # protocol path (with a no-op-ish preprocessor) agrees
    class Lower:
        def pre_process(self, tok):
            return tok.lower()
    t2 = Tokenizer(["A", "", "B"], Lower())
    assert t2.get_tokens() == ["a", "b"]


def test_encoded_corpus_matches_per_sentence_encode():
    """The r5 one-pass vectorized _encoded_corpus == the per-sentence
    _encode reference (unknown words dropped, kept-lengths match)."""
    w = Word2Vec(sentences=_toy_corpus(6), layer_size=8, window=2,
                 epochs=1, seed=3, min_word_frequency=2, negative=2)
    w.build_vocab()
    flat, lens = w._encoded_corpus()
    ref_seqs = [w._encode(s) for s in w._tokenized_corpus()]
    ref_flat = (np.concatenate(ref_seqs) if ref_seqs
                else np.empty(0, np.int32))
    np.testing.assert_array_equal(flat, ref_flat)
    np.testing.assert_array_equal(lens,
                                  [len(s) for s in ref_seqs])


def test_build_vocab_rereads_changed_corpus():
    """A vocab rebuild must see the CURRENT corpus, not a stale token
    cache (advisor-style regression for the r5 token cache)."""
    from deeplearning4j_tpu.nlp.sentenceiterator import \
        CollectionSentenceIterator
    w = Word2Vec(sentences=["aa bb cc"] * 3, layer_size=8, window=2,
                 epochs=1, seed=3, min_word_frequency=1, negative=2)
    w.build_vocab()
    assert w.vocab.contains_word("aa")
    w.sentence_iterator = CollectionSentenceIterator(["xx yy zz"] * 3)
    w.vocab = None
    w.build_vocab()
    assert w.vocab.contains_word("xx")
    assert not w.vocab.contains_word("aa")


def test_hs_scanned_then_stepped_same_model():
    """The device-resident HS tables are PRIVATE copies: the scanned
    fit's buffer donation must not delete the lookup table's own
    Huffman arrays, so a stepped fit on the same model still works
    (r5 review — 'Array has been deleted' on donating backends)."""
    w = Word2Vec(sentences=_toy_corpus(8), layer_size=16, window=3,
                 epochs=1, seed=13, min_word_frequency=2, batch_size=64,
                 negative=0, use_hierarchic_softmax=True)
    w.fit()                      # scanned path donates table carries
    # the table arrays are still alive and usable by the stepped path
    assert np.isfinite(np.asarray(w.lookup_table.points)).all()
    w.scan_epochs = False
    w.fit()                      # stepped path gathers from lt.points
    assert np.isfinite(np.asarray(w.lookup_table.syn0)).all()


def test_empty_sentences_do_not_misalign_corpus():
    """Blank sentences through subclasses that do not pre-filter must
    not break the one-pass encoder's sentence-boundary bookkeeping
    (r5 review: reduceat needs strictly increasing starts)."""
    from deeplearning4j_tpu.scaleout.sequencevectors import SparkWord2Vec
    sv = SparkWord2Vec(sentences=["hello world hello", "", "   ",
                                  "more text more"] * 4,
                       layer_size=8, window=2, epochs=1, seed=3,
                       min_word_frequency=1, negative=2)
    sv.build_vocab()
    flat, lens = sv._encoded_corpus()
    assert int(lens.sum()) == len(flat)
    assert (lens > 0).all()
    c, x = sv._corpus_window_pairs()
    assert len(c) == len(x) > 0


def test_distributed_build_vocab_resets_staging_caches():
    """DistributedSequenceVectors.build_vocab must drop the token and
    encoded-corpus caches (r5 review: rebuild on a changed corpus
    silently trained on the old corpus's ids)."""
    from deeplearning4j_tpu.scaleout.sequencevectors import SparkWord2Vec
    sv = SparkWord2Vec(sentences=["aa bb cc aa"] * 4, layer_size=8,
                       window=2, epochs=1, seed=3, min_word_frequency=1,
                       negative=2)
    sv.build_vocab()
    sv._encoded_corpus()
    sv.corpus = ["xx yy zz xx"] * 4
    sv.build_vocab()
    assert sv.vocab.contains_word("xx")
    flat, _ = sv._encoded_corpus()
    words = [sv.vocab.word_at_index(int(i)).word for i in flat[:4]]
    assert set(words) <= {"xx", "yy", "zz"}
