"""End-to-end training: score decreases, accuracy improves (reference test
analog: deeplearning4j-core/src/test/.../nn/multilayer/ integration tests on
Iris/MNIST)."""
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets import (DigitsDataSetIterator,
                                         IrisDataSetIterator)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          GravesLSTM, OutputLayer,
                                          RnnOutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.train.listeners import CollectScoresIterationListener


def test_iris_mlp_learns():
    conf = (NeuralNetConfiguration(seed=42, updater="adam",
                                   learning_rate=0.01, activation="tanh")
            .list(DenseLayer(n_in=4, n_out=16),
                  OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="mcxent")))
    net = MultiLayerNetwork(conf).init()
    it = IrisDataSetIterator(batch_size=150)
    collector = CollectScoresIterationListener()
    net.set_listeners(collector)
    first_score = None
    for epoch in range(200):
        net.fit(it)
        if first_score is None:
            first_score = collector.scores[0][1]
    final_score = collector.scores[-1][1]
    assert final_score < first_score * 0.5
    ev = net.evaluate(IrisDataSetIterator(batch_size=150))
    assert ev.accuracy() > 0.95


def test_digits_cnn_learns():
    conf = (NeuralNetConfiguration(seed=7, updater="adam",
                                   learning_rate=5e-3)
            .list(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                   activation="relu"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                  DenseLayer(n_out=32, activation="relu"),
                  OutputLayer(n_out=10, activation="softmax",
                              loss_function="mcxent"))
            .set_input_type(InputType.convolutional_flat(8, 8, 1)))
    net = MultiLayerNetwork(conf).init()
    it = DigitsDataSetIterator(batch_size=128)
    for _ in range(8):
        net.fit(it)
    ev = net.evaluate(DigitsDataSetIterator(batch_size=128))
    assert ev.accuracy() > 0.85


def test_rnn_sequence_classification():
    # each timestep's label = class of the sequence; simple separable task
    rng = np.random.RandomState(0)
    n, t, f, c = 64, 12, 5, 3
    labels = rng.randint(0, c, n)
    x = rng.randn(n, t, f).astype(np.float32) * 0.1
    for i in range(n):
        x[i, :, labels[i] % f] += 1.0
    y = np.zeros((n, t, c), np.float32)
    y[np.arange(n), :, labels] = 1.0

    conf = (NeuralNetConfiguration(seed=1, updater="adam",
                                   learning_rate=0.02)
            .list(GravesLSTM(n_in=f, n_out=12, activation="tanh"),
                  RnnOutputLayer(n_in=12, n_out=c, activation="softmax",
                                 loss_function="mcxent")))
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(x, y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score(x, y) < s0 * 0.3


def test_tbptt_runs():
    rng = np.random.RandomState(3)
    x = rng.randn(4, 25, 3).astype(np.float32)
    y = np.tile(np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)][:, None],
                (1, 25, 1))
    conf = (NeuralNetConfiguration(seed=1, learning_rate=0.05)
            .list(GravesLSTM(n_in=3, n_out=6),
                  RnnOutputLayer(n_in=6, n_out=2, activation="softmax"))
            .backprop_type_tbptt(10, 10))
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y)
    assert np.isfinite(float(net.score_value))
    # 25 steps with chunks of 10 -> 3 chunk iterations
    assert net.iteration_count == 3


def test_rnn_time_step_streaming():
    conf = (NeuralNetConfiguration(seed=5)
            .list(GravesLSTM(n_in=3, n_out=4),
                  RnnOutputLayer(n_in=4, n_out=2, activation="softmax")))
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(0).randn(2, 6, 3).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    step_outs = []
    for t in range(6):
        step_outs.append(np.asarray(net.rnn_time_step(x[:, t])))
    streamed = np.stack(step_outs, axis=1)
    np.testing.assert_allclose(streamed, full, rtol=1e-4, atol=1e-5)


def test_l2_regularization_changes_score():
    it = IrisDataSetIterator(batch_size=150)
    conf = (NeuralNetConfiguration(seed=42, l2=0.1)
            .list(DenseLayer(n_in=4, n_out=8),
                  OutputLayer(n_in=8, n_out=3, activation="softmax")))
    net = MultiLayerNetwork(conf).init()
    conf2 = (NeuralNetConfiguration(seed=42)
             .list(DenseLayer(n_in=4, n_out=8),
                   OutputLayer(n_in=8, n_out=3, activation="softmax")))
    net2 = MultiLayerNetwork(conf2).init()
    batch = next(iter(it))
    s_reg = net.score(batch.features, batch.labels)
    s_noreg = net2.score(batch.features, batch.labels)
    assert s_reg > s_noreg  # penalty adds positive mass


def test_params_flat_roundtrip():
    conf = (NeuralNetConfiguration(seed=1)
            .list(DenseLayer(n_in=4, n_out=5),
                  OutputLayer(n_in=5, n_out=3, activation="softmax")))
    net = MultiLayerNetwork(conf).init()
    flat = net.params_flat()
    assert flat.shape[0] == net.num_params() == (4 * 5 + 5) + (5 * 3 + 3)
    net.set_params_flat(np.zeros_like(np.asarray(flat)))
    assert float(np.abs(np.asarray(net.params_flat())).max()) == 0.0


def test_frozen_layer_does_not_update():
    from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
    conf = (NeuralNetConfiguration(seed=1, learning_rate=0.1)
            .list(FrozenLayer(inner=DenseLayer(n_in=4, n_out=5,
                                               activation="tanh")),
                  OutputLayer(n_in=5, n_out=3, activation="softmax")))
    net = MultiLayerNetwork(conf).init()
    w_before = np.asarray(net.params["layer_0"]["W"]).copy()
    it = IrisDataSetIterator(batch_size=50)
    net.fit(it)
    w_after = np.asarray(net.params["layer_0"]["W"])
    np.testing.assert_array_equal(w_before, w_after)
    # but the output layer did move
    assert net.iteration_count > 0


def test_rbm_pretrain_reduces_free_energy_gap():
    """RBM CD-1 pretraining learns the data distribution (reference:
    RBM contrastive divergence; analog of the reference's RBM pretrain
    tests)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import RBM
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    rng = np.random.default_rng(0)
    # binary patterns: two prototypes + flip noise
    protos = np.array([[1, 1, 1, 1, 0, 0, 0, 0],
                       [0, 0, 0, 0, 1, 1, 1, 1]], np.float32)
    idx = rng.integers(0, 2, 128)
    x = protos[idx]
    flip = rng.random(x.shape) < 0.05
    x = np.abs(x - flip.astype(np.float32))

    conf = NeuralNetConfiguration(seed=1, updater="sgd",
                                  learning_rate=0.1).list(
        RBM(n_in=8, n_out=6),
        OutputLayer(n_out=2, activation="softmax",
                    loss_function="mcxent"))
    conf.set_pretrain(True)
    net = MultiLayerNetwork(conf).init()
    rbm = net.layers[0]

    def fe(v):
        return float(np.mean(np.asarray(
            rbm._free_energy(net.params["layer_0"], jnp.asarray(v)))))

    rand_v = rng.integers(0, 2, x.shape).astype(np.float32)
    gap_before = fe(rand_v) - fe(x)
    for _ in range(30):
        net.pretrain_layer(0, x)
    gap_after = fe(rand_v) - fe(x)
    # after training, data should have much lower free energy than noise
    assert gap_after > gap_before + 1.0, (gap_before, gap_after)
    # supervised forward still works on top
    h, _ = rbm.apply(net.params["layer_0"], {}, jnp.asarray(x[:4]))
    assert h.shape == (4, 6)


def test_fit_batched_matches_per_step_fit():
    """The scanned whole-epoch program (fit_batched: lax.scan of the
    minibatch step, per-step loop on device) must be numerically
    equivalent to driving the same minibatches through per-step fit()."""
    rng = np.random.default_rng(3)
    n_steps, batch = 5, 32
    xs = rng.random((n_steps, batch, 4), dtype=np.float32)
    labels = rng.integers(0, 3, (n_steps, batch))
    ys = np.eye(3, dtype=np.float32)[labels]

    def make_net():
        conf = (NeuralNetConfiguration(seed=11, updater="adam",
                                       learning_rate=0.05,
                                       activation="tanh")
                .list(DenseLayer(n_in=4, n_out=8),
                      OutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent")))
        return MultiLayerNetwork(conf).init()

    ref = make_net()
    ref_scores = []
    collector = CollectScoresIterationListener()
    ref.set_listeners(collector)
    for i in range(n_steps):
        ref.fit(xs[i], ys[i])
    ref_scores = [s for _, s in collector.scores]

    net = make_net()
    scores = np.asarray(net.fit_batched(xs, ys))
    assert scores.shape == (n_steps,)
    np.testing.assert_allclose(scores, ref_scores, rtol=1e-4, atol=1e-5)
    assert net.iteration_count == n_steps
    ref_flat = np.asarray(ref.params_flat())
    net_flat = np.asarray(net.params_flat())
    np.testing.assert_allclose(net_flat, ref_flat, rtol=1e-4, atol=1e-5)


def test_fit_batched_epochs_matches_sequential_calls():
    """fit_batched(xs, ys, epochs=3) — the nested-scan multi-pass
    program — must equal three sequential fit_batched(xs, ys) calls
    exactly (iteration counter, dropout keys, and LR schedule position
    all advance identically across the in-program passes)."""
    rng = np.random.default_rng(5)
    n_steps, batch = 4, 16
    xs = rng.random((n_steps, batch, 4), dtype=np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (n_steps, batch))]

    def make_net():
        conf = (NeuralNetConfiguration(seed=11, updater="adam",
                                       learning_rate=0.05,
                                       activation="tanh", dropout=0.25)
                .list(DenseLayer(n_in=4, n_out=8),
                      OutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent")))
        return MultiLayerNetwork(conf).init()

    ref = make_net()
    ref_scores = np.concatenate(
        [np.asarray(ref.fit_batched(xs, ys)) for _ in range(3)])

    net = make_net()
    scores = np.asarray(net.fit_batched(xs, ys, epochs=3))
    assert scores.shape == (3 * n_steps,)
    np.testing.assert_allclose(scores, ref_scores, rtol=1e-5, atol=1e-6)
    assert net.iteration_count == 3 * n_steps
    np.testing.assert_allclose(np.asarray(net.params_flat()),
                               np.asarray(ref.params_flat()),
                               rtol=1e-5, atol=1e-6)


def test_fit_batched_tbptt_matches_per_chunk_fit():
    """Scanned TBPTT (fit_batched on a tbptt config: inner chunk scan
    with carried RNN state, one update per chunk) == per-minibatch
    fit(), which dispatches the host-loop _fit_tbptt path."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
    from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTM

    rng = np.random.default_rng(2)
    n_steps, batch, T, F = 3, 8, 8, 5
    xs = rng.random((n_steps, batch, T, F), dtype=np.float32)
    ys = np.eye(F, dtype=np.float32)[
        rng.integers(0, F, (n_steps, batch, T))]

    def make_net():
        conf = (NeuralNetConfiguration(seed=21, updater="rmsprop",
                                       learning_rate=0.05)
                .list(GravesLSTM(n_out=12, activation="tanh"),
                      RnnOutputLayer(n_out=F, activation="softmax",
                                     loss_function="mcxent"))
                .set_input_type(InputType.recurrent(F)))
        conf.backprop_type_tbptt(4, 4)       # T=8 -> 2 chunks/minibatch
        return MultiLayerNetwork(conf).init()

    ref = make_net()
    for i in range(n_steps):
        ref.fit(xs[i], ys[i])

    net = make_net()
    scores = np.asarray(net.fit_batched(xs, ys))
    assert scores.shape == (n_steps * 2,)    # one score per chunk
    assert net.iteration_count == ref.iteration_count == n_steps * 2
    np.testing.assert_allclose(np.asarray(net.params_flat()),
                               np.asarray(ref.params_flat()),
                               rtol=1e-4, atol=1e-5)


def test_graph_fit_batched_tbptt_matches_per_chunk_fit():
    """ComputationGraph scanned TBPTT == per-minibatch fit() (the
    doTruncatedBPTT analog), same contract as the MLN twin."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
    from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTM

    rng = np.random.default_rng(4)
    n_steps, batch, T, F = 3, 8, 8, 5
    xs = rng.random((n_steps, batch, T, F), dtype=np.float32)
    ys = np.eye(F, dtype=np.float32)[
        rng.integers(0, F, (n_steps, batch, T))]

    def make_net():
        conf = (NeuralNetConfiguration(seed=31, updater="rmsprop",
                                       learning_rate=0.05)
                .graph_builder()
                .add_inputs("in")
                .add_layer("lstm", GravesLSTM(n_out=12,
                                              activation="tanh"), "in")
                .add_layer("out", RnnOutputLayer(n_out=F,
                                                 activation="softmax",
                                                 loss_function="mcxent"),
                           "lstm")
                .set_outputs("out")
                .set_input_types(**{"in": InputType.recurrent(F)})
                .backprop_type_tbptt(4, 4)
                .build())
        return ComputationGraph(conf).init()

    ref = make_net()
    for i in range(n_steps):
        ref.fit(xs[i], ys[i])

    net = make_net()
    scores = np.asarray(net.fit_batched(xs, ys))
    assert scores.shape == (n_steps * 2,)
    assert net.iteration_count == ref.iteration_count == n_steps * 2
    np.testing.assert_allclose(np.asarray(net.params_flat()),
                               np.asarray(ref.params_flat()),
                               rtol=1e-4, atol=1e-5)


def test_output_and_evaluate_batched_match_per_batch():
    """Scanned inference (output_batched/evaluate_batched) == per-batch
    output()/evaluate() over the same pool."""
    conf = (NeuralNetConfiguration(seed=3, updater="adam",
                                   learning_rate=0.05, activation="tanh")
            .list(DenseLayer(n_in=4, n_out=8),
                  OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent")))
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    xs = rng.random((5, 16, 4), dtype=np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (5, 16))]
    net.fit_batched(xs, ys, epochs=2)

    pooled = np.asarray(net.output_batched(xs))
    per_batch = np.stack([np.asarray(net.output(xs[i]))
                          for i in range(5)])
    np.testing.assert_allclose(pooled, per_batch, rtol=1e-5, atol=1e-6)

    from deeplearning4j_tpu.eval.evaluation import Evaluation
    ev = net.evaluate_batched(xs, ys)
    ref = Evaluation()
    ref.eval(ys.reshape(-1, 3), per_batch.reshape(-1, 3))
    assert abs(ev.accuracy() - ref.accuracy()) < 1e-9


def test_graph_output_and_evaluate_batched():
    """DAG twin of the scanned inference path."""
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph

    conf = (NeuralNetConfiguration(seed=9, updater="adam",
                                   learning_rate=0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_in=6, n_out=10,
                                       activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=10, n_out=2,
                                          activation="softmax",
                                          loss_function="mcxent"), "h")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(1)
    xs = rng.random((4, 16, 6), dtype=np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 16))]
    net.fit_batched(xs, ys)

    pooled = np.asarray(net.output_batched(xs)[0])
    per_batch = np.stack([np.asarray(net.output(xs[i])[0])
                          for i in range(4)])
    np.testing.assert_allclose(pooled, per_batch, rtol=1e-5, atol=1e-6)
    ev = net.evaluate_batched(xs, ys)
    assert 0.0 <= ev.accuracy() <= 1.0


def test_fit_batched_learns_digits():
    conf = (NeuralNetConfiguration(seed=7, updater="adam",
                                   learning_rate=5e-3)
            .list(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                   activation="relu"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                  DenseLayer(n_out=32, activation="relu"),
                  OutputLayer(n_out=10, activation="softmax",
                              loss_function="mcxent"))
            .set_input_type(InputType.convolutional_flat(8, 8, 1)))
    net = MultiLayerNetwork(conf).init()
    it = DigitsDataSetIterator(batch_size=128)
    batches = [(np.asarray(b.features), np.asarray(b.labels)) for b in it]
    # stack only the full-size batches for the scan (static shapes)
    full = [(f, l) for f, l in batches if f.shape[0] == 128]
    xs = np.stack([f for f, _ in full])
    ys = np.stack([l for _, l in full])
    scores = None
    for _ in range(10):
        scores = np.asarray(net.fit_batched(xs, ys))
    ev = net.evaluate(DigitsDataSetIterator(batch_size=128))
    assert ev.accuracy() > 0.85
    assert scores[-1] < 1.0


def test_graph_fit_batched_matches_per_step_fit():
    """ComputationGraph.fit_batched (scanned DAG epoch) equals per-step
    fit() on the same minibatches."""
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph

    rng = np.random.default_rng(5)
    n_steps, batch = 4, 16
    xs = rng.random((n_steps, batch, 6), dtype=np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (n_steps, batch))]

    def make():
        conf = (NeuralNetConfiguration(seed=9, updater="adam",
                                       learning_rate=0.05)
                .graph_builder()
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_in=6, n_out=10,
                                           activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_in=10, n_out=2,
                                              activation="softmax",
                                              loss_function="mcxent"), "h")
                .set_outputs("out")
                .build())
        return ComputationGraph(conf).init()

    ref = make()
    for i in range(n_steps):
        ref.fit(xs[i], ys[i])
    net = make()
    scores = np.asarray(net.fit_batched(xs, ys))
    assert scores.shape == (n_steps,)
    np.testing.assert_allclose(np.asarray(net.params_flat()),
                               np.asarray(ref.params_flat()),
                               rtol=1e-4, atol=1e-5)


def test_graph_fit_batched_rejects_second_order():
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    conf = (NeuralNetConfiguration(seed=1, optimization_algo="lbfgs")
            .graph_builder()
            .add_inputs("in")
            .add_layer("out", OutputLayer(n_in=4, n_out=2,
                                          activation="softmax",
                                          loss_function="mcxent"), "in")
            .set_outputs("out").build())
    g = ComputationGraph(conf).init()
    with pytest.raises(ValueError, match="first-order"):
        g.fit_batched(np.zeros((2, 8, 4), np.float32),
                      np.zeros((2, 8, 2), np.float32))


def test_graph_tbptt_and_rnn_time_step():
    """ComputationGraph TBPTT + streaming (reference:
    ComputationGraph.doTruncatedBPTT:2042, rnnTimeStep)."""
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph

    rng = np.random.RandomState(4)
    x = rng.randn(4, 20, 3).astype(np.float32)
    y = np.tile(np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)][:, None],
                (1, 20, 1))
    conf = (NeuralNetConfiguration(seed=1, learning_rate=0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=6), "in")
            .add_layer("out", RnnOutputLayer(n_in=6, n_out=2,
                                             activation="softmax"),
                       "lstm")
            .set_outputs("out")
            .backprop_type_tbptt(8, 8)
            .build())
    g = ComputationGraph(conf).init()
    g.fit(x, y)
    assert np.isfinite(float(g.score_value))
    # 20 steps, chunks of 8 -> 3 chunk iterations
    assert g.iteration_count == 3

    # streaming: per-timestep output == full-sequence forward
    g.rnn_clear_previous_state()
    full = np.asarray(g.output(x)[0])
    steps = [np.asarray(g.rnn_time_step(x[:, t])[0]) for t in range(20)]
    np.testing.assert_allclose(np.stack(steps, 1), full, rtol=2e-3,
                               atol=2e-3)


def test_classifier_convenience_methods():
    """predict / f1_score / label_probabilities / num_labels / summary /
    score_examples / rnn state get-set (reference: Classifier interface +
    MultiLayerNetwork conveniences)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((24, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
    conf = (NeuralNetConfiguration(seed=1, updater="adam",
                                   learning_rate=0.05, l2=0.01,
                                   activation="tanh")
            .list(DenseLayer(n_in=4, n_out=8),
                  OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent")))
    net = MultiLayerNetwork(conf).init()
    for _ in range(10):
        net.fit(x, y)
    preds = net.predict(x)
    assert preds.shape == (24,) and preds.max() < 3
    probs = np.asarray(net.label_probabilities(x))
    np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-4)
    assert net.num_labels() == 3
    assert 0.0 <= net.f1_score(x, y) <= 1.0
    per = net.score_examples(x, y)
    assert per.shape == (24,)
    np.testing.assert_allclose(per.mean(), net.score(x, y), rtol=0.05)
    s = net.summary()
    assert "Total parameters" in s and "DenseLayer" in s
    acts = net.feed_forward_to_layer(0, x)
    assert len(acts) == 1 and np.asarray(acts[0]).shape == (24, 8)

    # rnn state get/set round trip
    rconf = (NeuralNetConfiguration(seed=2)
             .list(GravesLSTM(n_in=3, n_out=4),
                   RnnOutputLayer(n_in=4, n_out=2, activation="softmax")))
    rnet = MultiLayerNetwork(rconf).init()
    xa = rng.standard_normal((2, 5, 3)).astype(np.float32)
    rnet.rnn_time_step(xa[:, 0])
    st = rnet.rnn_get_previous_state(0)
    assert st is not None
    out_a = np.asarray(rnet.rnn_time_step(xa[:, 1]))
    rnet.rnn_set_previous_state(0, st)  # rewind
    out_b = np.asarray(rnet.rnn_time_step(xa[:, 1]))
    np.testing.assert_allclose(out_a, out_b, rtol=1e-5)


def test_graph_classifier_conveniences():
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    rng = np.random.default_rng(1)
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    conf = (NeuralNetConfiguration(seed=1, updater="adam",
                                   learning_rate=0.05, l2=0.01,
                                   activation="tanh")
            .graph_builder().add_inputs("in")
            .add_layer("h", DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=2,
                                          activation="softmax",
                                          loss_function="mcxent"), "h")
            .set_outputs("out").build())
    g = ComputationGraph(conf).init()
    for _ in range(40):
        g.fit(x, y)
    preds = g.predict(x)
    assert (preds == y.argmax(1)).mean() > 0.9
    assert g.f1_score(x, y) > 0.9
    per = g.score_examples(x, y)
    assert per.shape == (32,)
    np.testing.assert_allclose(per.mean(), g.score(x, y), rtol=0.05)
    per_noreg = g.score_examples(x, y, add_regularization_terms=False)
    assert (per_noreg < per).all()
    s = g.summary()
    assert "Total parameters" in s and "out" in s
