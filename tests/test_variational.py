"""VAE tests (reference analogs: VaeGradientCheckTests.java, the
variational reconstruction-distribution suite, and
TestVAE.reconstructionProbability in deeplearning4j-core)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.layers.variational import VariationalAutoencoder
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _vae(recon="gaussian", n_in=8):
    conf = NeuralNetConfiguration(seed=3, updater="adam",
                                  learning_rate=0.01).list(
        VariationalAutoencoder(n_in=n_in, n_out=3,
                               encoder_layer_sizes=(12,),
                               decoder_layer_sizes=(12,),
                               reconstruction_distribution=recon),
        OutputLayer(n_out=2, activation="softmax",
                    loss_function="mcxent"))
    conf.set_pretrain(True)
    return MultiLayerNetwork(conf).init()


def _data(n=64, n_in=8, seed=0, binary=False):
    rng = np.random.default_rng(seed)
    if binary:
        return (rng.random((n, n_in)) < 0.4).astype(np.float32)
    return rng.normal(0.5, 0.2, (n, n_in)).astype(np.float32)


def test_vae_pretrain_reduces_elbo_loss():
    net = _vae("gaussian")
    x = _data()
    vae = net.layers[0]
    key = jax.random.PRNGKey(0)
    before = float(vae.pretrain_loss(net.params["layer_0"],
                                     jnp.asarray(x), key))
    for _ in range(60):
        net.pretrain_layer(0, x)
    after = float(vae.pretrain_loss(net.params["layer_0"],
                                    jnp.asarray(x), key))
    assert after < before


def test_vae_reconstruction_prob_higher_for_in_distribution():
    net = _vae("bernoulli")
    x = _data(binary=True)
    for _ in range(80):
        net.pretrain_layer(0, x)
    vae = net.layers[0]
    key = jax.random.PRNGKey(7)
    lp_data = np.asarray(vae.reconstruction_prob(
        net.params["layer_0"], jnp.asarray(x[:16]), key, num_samples=8))
    noise = (np.random.default_rng(9).random((16, 8)) < 0.9
             ).astype(np.float32)
    lp_noise = np.asarray(vae.reconstruction_prob(
        net.params["layer_0"], jnp.asarray(noise), key, num_samples=8))
    assert lp_data.mean() > lp_noise.mean()


def test_vae_composite_reconstruction_distribution():
    """First 5 features gaussian, last 3 bernoulli (reference:
    CompositeReconstructionDistribution.addDistribution)."""
    comp = ((5, "gaussian"), (3, "bernoulli"))
    net = _vae(comp)
    vae = net.layers[0]
    # decoder head sizes: 5*2 + 3*1
    assert vae._recon_out_size() == 13
    assert net.params["layer_0"]["xW"].shape[1] == 13
    rng = np.random.default_rng(1)
    x = np.concatenate([
        rng.normal(0.0, 1.0, (32, 5)),
        (rng.random((32, 3)) < 0.5).astype(float)], axis=1
    ).astype(np.float32)
    key = jax.random.PRNGKey(0)
    before = float(vae.pretrain_loss(net.params["layer_0"],
                                     jnp.asarray(x), key))
    assert np.isfinite(before)
    for _ in range(40):
        net.pretrain_layer(0, x)
    after = float(vae.pretrain_loss(net.params["layer_0"],
                                    jnp.asarray(x), key))
    assert after < before
    # composite log-prob == sum of slice log-probs computed independently
    raw = jnp.asarray(rng.normal(size=(4, 13)).astype(np.float32))
    xs = jnp.asarray(x[:4])
    got = vae._recon_log_prob(raw, xs)
    want = (vae._component_log_prob("gaussian", raw[:, :10], xs[:, :5])
            + vae._component_log_prob("bernoulli", raw[:, 10:], xs[:, 5:]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6)


def test_vae_composite_size_mismatch_raises():
    import pytest
    with pytest.raises(ValueError, match="covers 6"):
        _vae(((3, "gaussian"), (3, "bernoulli")), n_in=8)


def test_vae_pretrain_loss_gradcheck():
    """Central-difference check of the -ELBO gradient wrt VAE params
    (reference: VaeGradientCheckTests — same idea, AD vs numeric)."""
    net = _vae("gaussian")
    x = jnp.asarray(_data(n=8))
    vae = net.layers[0]
    key = jax.random.PRNGKey(5)
    params = jax.tree.map(lambda a: a.astype(jnp.float64)
                          if jnp.issubdtype(a.dtype, jnp.floating) else a,
                          net.params["layer_0"])

    def loss(p):
        return vae.pretrain_loss(p, x.astype(jnp.float64), key)

    grads = jax.grad(loss)(params)
    flat, unravel = jax.flatten_util.ravel_pytree(params)
    gflat = jax.flatten_util.ravel_pytree(grads)[0]
    rng = np.random.default_rng(0)
    idx = rng.choice(flat.shape[0], size=40, replace=False)
    eps = 1e-5
    for i in idx:
        e = jnp.zeros_like(flat).at[i].set(eps)
        num = (float(loss(unravel(flat + e)))
               - float(loss(unravel(flat - e)))) / (2 * eps)
        ana = float(gflat[i])
        denom = max(abs(num), abs(ana), 1e-8)
        assert abs(num - ana) / denom < 1e-3 or abs(num - ana) < 1e-7, (
            i, num, ana)


def test_async_multi_dataset_iterator():
    from deeplearning4j_tpu.datasets import AsyncMultiDataSetIterator
    from deeplearning4j_tpu.datasets.records import MultiDataSet
    base = [MultiDataSet(features=[np.ones((4, 2)) * i],
                         labels=[np.zeros((4, 1))]) for i in range(5)]
    it = AsyncMultiDataSetIterator(base, queue_size=2)
    seen = [float(np.asarray(m.features[0]).mean()) for m in it]
    assert seen == [0.0, 1.0, 2.0, 3.0, 4.0]
    it.reset()
    assert len(list(it)) == 5


def test_recursive_tree():
    """Tree structure parity (reference: recursive/Tree.java)."""
    from deeplearning4j_tpu.util.tree import Tree
    root = Tree()
    root.set_label("S")
    np_ = root.add_child(Tree())
    np_.set_label("NP")
    vp = root.add_child(Tree())
    vp.set_label("VP")
    the = np_.add_child(Tree(["the"]))
    cat = np_.add_child(Tree(["cat"]))
    sat = vp.add_child(Tree(["sat"]))
    assert root.yield_() == ["the", "cat", "sat"]
    assert root.depth() == 2
    assert the.is_leaf() and not np_.is_leaf()
    assert np_.is_pre_terminal() and not root.is_pre_terminal()
    assert [t.tokens[0] for t in root.get_leaves()] == ["the", "cat",
                                                        "sat"]
    assert root.distance_to(cat) == 2
    assert cat.ancestor(2) is root
    np_.error_value = 0.5
    cat.error_value = 0.25
    assert root.error_sum() == 0.75
    c = root.clone()
    assert c.yield_() == root.yield_()
    assert c is not root and c.children()[0] is not np_
    assert root.first_child() is np_ and root.last_child() is vp


def test_graph_pretrain_vae_vertex():
    """ComputationGraph layerwise pretraining (reference:
    ComputationGraph.pretrain:527) — a VAE vertex behind a frozen dense
    vertex learns to reconstruct."""
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration(seed=3, updater="adam",
                                   learning_rate=0.01, activation="tanh")
            .graph_builder().add_inputs("in")
            .add_layer("d", DenseLayer(n_in=8, n_out=6), "in")
            .add_layer("vae", VariationalAutoencoder(
                n_in=6, n_out=2, encoder_layer_sizes=(10,),
                decoder_layer_sizes=(10,),
                reconstruction_distribution="gaussian"), "d")
            .add_layer("out", OutputLayer(n_in=2, n_out=2,
                                          activation="softmax",
                                          loss_function="mcxent"), "vae")
            .set_outputs("out").build())
    g = ComputationGraph(conf).init()
    x = _data(n=64, n_in=8)
    vae = conf.vertices["vae"].vertex
    d_before = np.asarray(g.params["d"]["W"]).copy()

    def vae_loss():
        import jax
        h = np.tanh(x @ np.asarray(g.params["d"]["W"])
                    + np.asarray(g.params["d"]["b"]))
        return float(vae.pretrain_loss(g.params["vae"], jnp.asarray(h),
                                       jax.random.PRNGKey(0)))

    before = vae_loss()
    for _ in range(50):
        g.pretrain(x)
    after = vae_loss()
    assert after < before
    # upstream vertex stayed frozen during pretraining
    np.testing.assert_array_equal(d_before, np.asarray(g.params["d"]["W"]))
