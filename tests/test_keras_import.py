"""Keras import golden-output tests.

Models the reference's KerasModelEndToEndTest: stored Keras HDF5 fixtures
are imported and predictions compared to independently computed outputs
(reference: deeplearning4j-modelimport/src/test/.../KerasModelEndToEndTest
loads fixtures from the dl4j-test-resources artifact). Since this
environment has no Keras and no network, fixtures are written in the exact
Keras-2 HDF5 layout with h5py and golden outputs computed in NumPy.
"""
import json

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")

from deeplearning4j_tpu.modelimport import (
    import_keras_sequential_model_and_weights,
    import_keras_model_and_weights, import_keras_model_configuration,
    vgg16)


def _write_keras_file(path, model_config, layer_weights, training_config=None):
    """Write the Keras-2 HDF5 layout: attrs model_config/training_config,
    group model_weights with layer_names + per-layer weight_names."""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_config).encode()
        if training_config is not None:
            f.attrs["training_config"] = json.dumps(training_config).encode()
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array(
            [n.encode() for n in layer_weights], dtype="S64")
        for lname, weights in layer_weights.items():
            g = mw.create_group(lname)
            g.attrs["weight_names"] = np.array(
                [wn.encode() for wn in weights], dtype="S64")
            for wn, arr in weights.items():
                g.create_dataset(wn, data=arr)


def _softmax(z):
    e = np.exp(z - z.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def test_sequential_dense_golden(tmp_path):
    rng = np.random.default_rng(0)
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)

    model_config = {
        "class_name": "Sequential",
        "keras_version": "2.1.0", "backend": "tensorflow",
        "config": [
            {"class_name": "Dense",
             "config": {"name": "dense_1", "units": 8, "activation": "relu",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "Dense",
             "config": {"name": "dense_2", "units": 3,
                        "activation": "softmax"}},
        ],
    }
    weights = {
        "dense_1": {"dense_1/kernel:0": w1, "dense_1/bias:0": b1},
        "dense_2": {"dense_2/kernel:0": w2, "dense_2/bias:0": b2},
    }
    path = str(tmp_path / "dense.h5")
    _write_keras_file(path, model_config, weights,
                      training_config={"loss": "categorical_crossentropy"})

    net = import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    want = _softmax(np.maximum(x @ w1 + b1, 0) @ w2 + b2)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sequential_conv_golden(tmp_path):
    rng = np.random.default_rng(1)
    k = rng.normal(size=(3, 3, 2, 4), scale=0.5).astype(np.float32)  # HWIO
    bk = rng.normal(size=(4,)).astype(np.float32)
    wd = rng.normal(size=(4, 3), scale=0.5).astype(np.float32)
    bd = rng.normal(size=(3,)).astype(np.float32)

    model_config = {
        "class_name": "Sequential",
        "keras_version": "2.1.0", "backend": "tensorflow",
        "config": [
            {"class_name": "Conv2D",
             "config": {"name": "conv", "filters": 4,
                        "kernel_size": [3, 3], "strides": [1, 1],
                        "padding": "same", "activation": "relu",
                        "data_format": "channels_last",
                        "batch_input_shape": [None, 6, 6, 2]}},
            {"class_name": "GlobalAveragePooling2D",
             "config": {"name": "gap"}},
            {"class_name": "Dense",
             "config": {"name": "out", "units": 3,
                        "activation": "softmax"}},
        ],
    }
    weights = {
        "conv": {"conv/kernel:0": k, "conv/bias:0": bk},
        "out": {"out/kernel:0": wd, "out/bias:0": bd},
    }
    path = str(tmp_path / "conv.h5")
    _write_keras_file(path, model_config, weights)

    net = import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
    got = np.asarray(net.output(x))

    # numpy reference: SAME conv + relu + global avg pool + dense softmax
    xp = np.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    conv = np.zeros((2, 6, 6, 4), np.float32)
    for i in range(6):
        for j in range(6):
            patch = xp[:, i:i + 3, j:j + 3, :]          # [B,3,3,2]
            conv[:, i, j, :] = np.tensordot(patch, k, axes=([1, 2, 3],
                                                            [0, 1, 2]))
    conv = np.maximum(conv + bk, 0)
    pooled = conv.mean(axis=(1, 2))
    want = _softmax(pooled @ wd + bd)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sequential_lstm_golden(tmp_path):
    rng = np.random.default_rng(2)
    H, F, T, B = 5, 3, 4, 2
    kernel = rng.normal(size=(F, 4 * H), scale=0.5).astype(np.float32)
    rker = rng.normal(size=(H, 4 * H), scale=0.5).astype(np.float32)
    bias = rng.normal(size=(4 * H,), scale=0.1).astype(np.float32)

    model_config = {
        "class_name": "Sequential",
        "keras_version": "2.1.0", "backend": "tensorflow",
        "config": [
            {"class_name": "LSTM",
             "config": {"name": "lstm", "units": H, "activation": "tanh",
                        "recurrent_activation": "sigmoid",
                        "return_sequences": True,
                        "batch_input_shape": [None, T, F]}},
        ],
    }
    weights = {"lstm": {"lstm/kernel:0": kernel,
                        "lstm/recurrent_kernel:0": rker,
                        "lstm/bias:0": bias}}
    path = str(tmp_path / "lstm.h5")
    _write_keras_file(path, model_config, weights)

    net = import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(B, T, F)).astype(np.float32)
    got = np.asarray(net.output(x))

    # numpy LSTM with keras gate order i,f,c,o (== framework i,f,g,o)
    def sig(v):
        return 1 / (1 + np.exp(-v))
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    outs = []
    for t in range(T):
        z = x[:, t] @ kernel + h @ rker + bias
        zi, zf, zg, zo = np.split(z, 4, axis=-1)
        i, f, g, o = sig(zi), sig(zf), np.tanh(zg), sig(zo)
        c = f * c + i * g
        h = o * np.tanh(c)
        outs.append(h)
    want = np.stack(outs, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_functional_model_with_add(tmp_path):
    rng = np.random.default_rng(3)
    w1 = rng.normal(size=(4, 6)).astype(np.float32)
    b1 = np.zeros(6, np.float32)
    w2 = rng.normal(size=(4, 6)).astype(np.float32)
    b2 = np.zeros(6, np.float32)
    wo = rng.normal(size=(6, 2)).astype(np.float32)
    bo = np.zeros(2, np.float32)

    def dense_cfg(name, units, act, **extra):
        c = {"name": name, "units": units, "activation": act}
        c.update(extra)
        return {"class_name": "Dense", "config": c, "name": name,
                "inbound_nodes": extra.pop("_inbound", [])}

    model_config = {
        "class_name": "Model",
        "keras_version": "2.1.0", "backend": "tensorflow",
        "config": {
            "name": "model_1",
            "layers": [
                {"class_name": "InputLayer", "name": "input_1",
                 "config": {"name": "input_1",
                            "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "branch_a",
                 "config": {"name": "branch_a", "units": 6,
                            "activation": "tanh"},
                 "inbound_nodes": [[["input_1", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "branch_b",
                 "config": {"name": "branch_b", "units": 6,
                            "activation": "tanh"},
                 "inbound_nodes": [[["input_1", 0, 0, {}]]]},
                {"class_name": "Add", "name": "add_1",
                 "config": {"name": "add_1"},
                 "inbound_nodes": [[["branch_a", 0, 0, {}],
                                    ["branch_b", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "out",
                 "config": {"name": "out", "units": 2,
                            "activation": "softmax"},
                 "inbound_nodes": [[["add_1", 0, 0, {}]]]},
            ],
            "input_layers": [["input_1", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
    }
    weights = {
        "branch_a": {"branch_a/kernel:0": w1, "branch_a/bias:0": b1},
        "branch_b": {"branch_b/kernel:0": w2, "branch_b/bias:0": b2},
        "out": {"out/kernel:0": wo, "out/bias:0": bo},
    }
    path = str(tmp_path / "func.h5")
    _write_keras_file(path, model_config, weights)

    net = import_keras_model_and_weights(path)
    x = rng.normal(size=(3, 4)).astype(np.float32)
    got = np.asarray(net.output(x)[0])
    want = _softmax((np.tanh(x @ w1 + b1) + np.tanh(x @ w2 + b2)) @ wo + bo)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_keras1_theano_conv_kernel_transposed(tmp_path):
    """Keras-1 config names + Theano OIHW kernel must be permuted to HWIO."""
    rng = np.random.default_rng(4)
    k_oihw = rng.normal(size=(4, 2, 3, 3), scale=0.5).astype(np.float32)
    bk = np.zeros(4, np.float32)

    model_config = {
        "class_name": "Sequential",
        "keras_version": "1.2.2", "backend": "theano",
        "config": [
            {"class_name": "Convolution2D",
             "config": {"name": "conv1", "nb_filter": 4, "nb_row": 3,
                        "nb_col": 3, "subsample": [1, 1],
                        "border_mode": "valid", "dim_ordering": "th",
                        "activation": "linear",
                        "batch_input_shape": [None, 2, 6, 6]}},
        ],
    }
    weights = {"conv1": {"conv1/kernel:0": k_oihw, "conv1/bias:0": bk}}
    path = str(tmp_path / "k1conv.h5")
    _write_keras_file(path, model_config, weights)

    net = import_keras_sequential_model_and_weights(path)
    w = np.asarray(net.params["conv1"]["W"])
    assert w.shape == (3, 3, 2, 4)
    np.testing.assert_allclose(w, np.transpose(k_oihw, (2, 3, 1, 0)),
                               rtol=1e-6)


def test_training_config_creates_output_layer(tmp_path):
    model_config = {
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense",
             "config": {"name": "d", "units": 2, "activation": "softmax",
                        "batch_input_shape": [None, 3]}},
        ],
    }
    w = np.eye(3, 2, dtype=np.float32)
    path = str(tmp_path / "tc.h5")
    _write_keras_file(path, model_config,
                      {"d": {"d/kernel:0": w,
                             "d/bias:0": np.zeros(2, np.float32)}},
                      training_config={"loss": "categorical_crossentropy"})
    net = import_keras_sequential_model_and_weights(path)
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    assert isinstance(net.layers[-1], OutputLayer)
    assert net.layers[-1].loss_function == "mcxent"
    # and it can train
    x = np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(
        0, 2, 8)]
    net.fit(x, y)


def test_config_only_json_roundtrip():
    conf = vgg16(num_classes=10, height=32, width=32)
    names = [l.name for l in conf.layers]
    assert names[0] == "block1_conv1" and names[-1] == "predictions"
    mc = {
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense",
             "config": {"name": "a", "units": 4, "activation": "relu",
                        "batch_input_shape": [None, 7]}},
            {"class_name": "Dropout", "config": {"name": "dr", "rate": 0.3}},
            {"class_name": "Dense",
             "config": {"name": "b", "units": 2, "activation": "softmax"}},
        ],
    }
    conf2 = import_keras_model_configuration(json.dumps(mc))
    assert len(conf2.layers) == 3


def test_vgg16_builds_and_infers_shapes():
    conf = vgg16(num_classes=10, height=64, width=64, dtype="float32")
    conf.resolve_shapes()
    # 13 convs + 5 pools + 2 fc + 1 output
    assert len(conf.layers) >= 21
    fc1 = [l for l in conf.layers if l.name == "fc1"][0]
    # 64/2^5 = 2 → 2*2*512 flattened
    assert fc1.n_in == 2 * 2 * 512


def test_functional_training_config_and_enforce(tmp_path):
    """Functional import maps training_config loss onto the output vertex;
    enforce_training_config fails fast without one."""
    import pytest as _pytest
    from deeplearning4j_tpu.modelimport import (
        InvalidKerasConfigurationException)
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    rng = np.random.default_rng(5)
    w = rng.normal(size=(4, 2)).astype(np.float32)
    model_config = {
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"class_name": "InputLayer", "name": "in",
                 "config": {"name": "in", "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "out",
                 "config": {"name": "out", "units": 2,
                            "activation": "softmax"},
                 "inbound_nodes": [[["in", 0, 0, {}]]]},
            ],
            "input_layers": [["in", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
    }
    weights = {"out": {"out/kernel:0": w,
                       "out/bias:0": np.zeros(2, np.float32)}}
    path = str(tmp_path / "func_tc.h5")
    _write_keras_file(path, model_config, weights,
                      training_config={"loss": "categorical_crossentropy"})
    net = import_keras_model_and_weights(path)
    out_vertex = net.conf.vertices["out"].vertex
    assert isinstance(out_vertex, OutputLayer)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    net.fit([x], [y])  # trains without error

    path2 = str(tmp_path / "func_notc.h5")
    _write_keras_file(path2, model_config, weights)
    with _pytest.raises(InvalidKerasConfigurationException):
        import_keras_model_and_weights(path2, enforce_training_config=True)


def test_resnet50_builds_and_runs_forward():
    """ResNet-50 graph (BASELINE.md's other Keras-import benchmark
    model): builds, inserts NO preprocessor anywhere — in particular no
    flattening CnnToFeedForward mid-residual (ActivationLayer/
    BatchNormalization declare input_family='any', and GlobalPooling
    already emits FF type, so the fc head needs no flatten either) —
    and runs forward at a small resolution."""
    from deeplearning4j_tpu.modelimport import resnet50
    from deeplearning4j_tpu.nn.graph.computation_graph import (
        ComputationGraph)

    conf = resnet50(num_classes=10, height=32, width=32, dtype="float32")
    graph = ComputationGraph(conf).init(seed=0)
    assert graph._preprocessors == {}
    out = graph.output(np.zeros((2, 32, 32, 3), np.float32))[0]
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(1), 1.0, rtol=1e-4)
