"""Long-tail util parity: Viterbi, SummaryStatistics, DataSet
normalization preprocessors, EvaluationTools HTML, early-stopping
listener (reference: util/Viterbi.java, util/SummaryStatistics.java,
datasets/.../{ZeroMean,UnitVariance,...}PreProcessor.java,
evaluation/EvaluationTools.java, earlystopping/listener/)."""
import numpy as np
import pytest


def test_viterbi_decodes_known_sequence():
    from deeplearning4j_tpu.util.viterbi import Viterbi
    # two states: sticky transitions; emissions strongly identify state
    trans = np.array([[0.9, 0.1], [0.1, 0.9]])
    v = Viterbi(trans)
    emissions = np.array([[0.9, 0.1]] * 4 + [[0.1, 0.9]] * 4)
    path, logp = v.decode(emissions)
    assert path.tolist() == [0, 0, 0, 0, 1, 1, 1, 1]
    assert np.isfinite(logp)
    # sticky prior smooths a single flickered observation
    emissions2 = np.array([[0.9, 0.1]] * 3 + [[0.45, 0.55]]
                          + [[0.9, 0.1]] * 3)
    path2, _ = v.decode(emissions2)
    assert path2.tolist() == [0] * 7


def test_summary_statistics_streaming():
    from deeplearning4j_tpu.util.berkeley import SummaryStatistics
    rng = np.random.default_rng(0)
    data = rng.normal(3.0, 2.0, 500)
    s = SummaryStatistics()
    s.add(data[:200])
    s.add(data[200:])
    assert s.n == 500
    np.testing.assert_allclose(s.mean, data.mean(), rtol=1e-9)
    np.testing.assert_allclose(s.std, data.std(), rtol=1e-9)
    assert s.min == data.min() and s.max == data.max()


def test_normalization_preprocessors():
    from deeplearning4j_tpu.datasets.iterators import (
        BinomialSamplingPreProcessor, DataSet, TestDataSetIterator,
        UnitVarianceProcessor, ZeroMeanAndUnitVariancePreProcessor,
        ZeroMeanPreProcessor)
    rng = np.random.default_rng(1)
    ds = DataSet(rng.normal(5.0, 3.0, (64, 4)).astype(np.float32),
                 np.zeros((64, 2), np.float32))
    zm = ZeroMeanPreProcessor().pre_process(ds)
    np.testing.assert_allclose(zm.features.mean(0), 0.0, atol=1e-5)
    uv = UnitVarianceProcessor().pre_process(ds)
    np.testing.assert_allclose(uv.features.std(0), 1.0, atol=1e-4)
    zs = ZeroMeanAndUnitVariancePreProcessor().pre_process(ds)
    np.testing.assert_allclose(zs.features.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(zs.features.std(0), 1.0, atol=1e-4)
    probs = DataSet(np.full((2000, 3), 0.3, np.float32),
                    np.zeros((2000, 1)))
    sampled = BinomialSamplingPreProcessor(seed=7).pre_process(probs)
    assert set(np.unique(sampled.features)) <= {0.0, 1.0}
    assert abs(sampled.features.mean() - 0.3) < 0.03
    # TestDataSetIterator batches a single DataSet
    sizes = [b.features.shape[0] for b in TestDataSetIterator(ds, 24)]
    assert sizes == [24, 24, 16]


def test_evaluation_tools_html(tmp_path):
    from deeplearning4j_tpu.eval.evaluation import Evaluation
    from deeplearning4j_tpu.eval.roc import ROC
    from deeplearning4j_tpu.eval.tools import (
        export_evaluation_to_html_file, export_roc_charts_to_html_file)
    l = np.array([0] * 10 + [1] * 10)
    p = np.clip(l + np.random.default_rng(0).normal(0, 0.3, 20), 0, 1)
    roc = ROC()
    roc.eval(np.eye(2)[l], np.stack([1 - p, p], 1))
    out = tmp_path / "roc.html"
    export_roc_charts_to_html_file(roc, str(out))
    html = out.read_text()
    assert "AUC" in html and "<svg" in html and "Precision" in html

    ev = Evaluation()
    ev.eval(np.eye(2)[l], np.stack([1 - p, p], 1))
    out2 = tmp_path / "eval.html"
    export_evaluation_to_html_file(ev, str(out2))
    html2 = out2.read_text()
    assert "Confusion" in html2 or "Accuracy" in html2


def test_early_stopping_listener_callbacks():
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.iterators import BaseDatasetIterator
    from deeplearning4j_tpu.earlystopping.config import \
        EarlyStoppingConfiguration
    from deeplearning4j_tpu.earlystopping.saver import InMemoryModelSaver
    from deeplearning4j_tpu.earlystopping.termination import \
        MaxEpochsTerminationCondition
    from deeplearning4j_tpu.earlystopping.trainer import (
        EarlyStoppingListener, EarlyStoppingTrainer)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    rng = np.random.default_rng(0)
    x = rng.random((32, 4), dtype=np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
    conf = (NeuralNetConfiguration(seed=1, learning_rate=0.05)
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_in=8, n_out=2, activation="softmax",
                              loss_function="mcxent")))
    net = MultiLayerNetwork(conf).init()

    events = []

    class Rec(EarlyStoppingListener):
        def on_start(self, config, net):
            events.append("start")

        def on_epoch(self, epoch, score, config, net):
            events.append(("epoch", epoch))

        def on_completion(self, result):
            events.append(("done", result.termination_reason))

    escfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
        model_saver=InMemoryModelSaver())
    trainer = EarlyStoppingTrainer(escfg, net,
                                   BaseDatasetIterator(x, y, 16),
                                   listener=Rec())
    result = trainer.fit()
    assert events[0] == "start"
    assert ("epoch", 0) in events
    assert events[-1] == ("done", "EpochTerminationCondition")
    assert result.total_epochs >= 3
