"""Multi-host (DCN) runtime tests: real separate processes joined by the
PJRT distributed runtime on CPU — the reference's local[N] Spark test
pattern (BaseSparkTest.java) upgraded to true multi-process
(SURVEY.md §4: "multi-host is simulated with multi-process local PJRT").
"""
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.multihost import MultiHostLauncher


def _psum_job():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np

    devs = jax.devices()  # global view across both processes
    mesh = Mesh(np.array(devs), ("data",))
    sharding = NamedSharding(mesh, P("data"))

    # each process contributes its local shard; psum must see ALL shards
    n = len(devs)
    x = jnp.arange(n, dtype=jnp.float32)

    @jax.jit
    def global_sum(x):
        return jnp.sum(x)

    xs = jax.device_put(x, sharding)
    total = float(global_sum(xs))
    return {"process": jax.process_index(),
            "processes": jax.process_count(),
            "global_devices": n,
            "local_devices": jax.local_device_count(),
            "sum": total}


@pytest.mark.slow
def test_two_process_distributed_psum():
    launcher = MultiHostLauncher(num_processes=2, devices_per_process=2)
    results = launcher.run(_psum_job, timeout=240)
    assert len(results) == 2
    for r in results:
        assert r["processes"] == 2
        assert r["global_devices"] == 4
        assert r["local_devices"] == 2
        assert r["sum"] == pytest.approx(sum(range(4)))
    assert {r["process"] for r in results} == {0, 1}


def _train_job():
    """Each process runs the SAME sharded train step over the global mesh
    — the data-parallel multi-host flow."""
    import jax
    import numpy as np
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from jax.sharding import Mesh

    conf = NeuralNetConfiguration(seed=5, learning_rate=0.1).list(
        DenseLayer(n_in=4, n_out=8, activation="tanh"),
        OutputLayer(n_out=2, activation="softmax",
                    loss_function="mcxent"))
    net = MultiLayerNetwork(conf).init()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    rng = np.random.default_rng(0)  # same data on every process
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    for _ in range(3):
        pw.fit(x, y)
    return {"process": jax.process_index(),
            "params": np.asarray(net.params_flat()).tolist()}


@pytest.mark.slow
def test_two_process_data_parallel_training_identical_params():
    launcher = MultiHostLauncher(num_processes=2, devices_per_process=2)
    results = launcher.run(_train_job, timeout=240)
    assert len(results) == 2
    p0 = np.asarray(results[0]["params"])
    p1 = np.asarray(results[1]["params"])
    # both hosts hold identical replicated parameters after training
    np.testing.assert_allclose(p0, p1, atol=1e-6)
