"""Multi-host (DCN) runtime tests: real separate processes joined by the
PJRT distributed runtime on CPU — the reference's local[N] Spark test
pattern (BaseSparkTest.java) upgraded to true multi-process
(SURVEY.md §4: "multi-host is simulated with multi-process local PJRT").
"""
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.multihost import MultiHostLauncher


def _psum_job():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np

    devs = jax.devices()  # global view across both processes
    mesh = Mesh(np.array(devs), ("data",))
    sharding = NamedSharding(mesh, P("data"))

    # each process contributes its local shard; psum must see ALL shards
    n = len(devs)
    x = jnp.arange(n, dtype=jnp.float32)

    @jax.jit
    def global_sum(x):
        return jnp.sum(x)

    xs = jax.device_put(x, sharding)
    total = float(global_sum(xs))
    return {"process": jax.process_index(),
            "processes": jax.process_count(),
            "global_devices": n,
            "local_devices": jax.local_device_count(),
            "sum": total}


@pytest.mark.slow
def test_two_process_distributed_psum():
    launcher = MultiHostLauncher(num_processes=2, devices_per_process=2)
    results = launcher.run(_psum_job, timeout=240)
    assert len(results) == 2
    for r in results:
        assert r["processes"] == 2
        assert r["global_devices"] == 4
        assert r["local_devices"] == 2
        assert r["sum"] == pytest.approx(sum(range(4)))
    assert {r["process"] for r in results} == {0, 1}


def _train_job():
    """Each process runs the SAME sharded train step over the global mesh
    — the data-parallel multi-host flow."""
    import jax
    import numpy as np
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from jax.sharding import Mesh

    conf = NeuralNetConfiguration(seed=5, learning_rate=0.1).list(
        DenseLayer(n_in=4, n_out=8, activation="tanh"),
        OutputLayer(n_out=2, activation="softmax",
                    loss_function="mcxent"))
    net = MultiLayerNetwork(conf).init()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    rng = np.random.default_rng(0)  # same data on every process
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    for _ in range(3):
        pw.fit(x, y)
    return {"process": jax.process_index(),
            "params": np.asarray(net.params_flat()).tolist()}


@pytest.mark.slow
def test_two_process_data_parallel_training_identical_params():
    launcher = MultiHostLauncher(num_processes=2, devices_per_process=2)
    results = launcher.run(_train_job, timeout=240)
    assert len(results) == 2
    p0 = np.asarray(results[0]["params"])
    p1 = np.asarray(results[1]["params"])
    # both hosts hold identical replicated parameters after training
    np.testing.assert_allclose(p0, p1, atol=1e-6)


def _mlp_conf():
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    return NeuralNetConfiguration(
        seed=5, learning_rate=0.1, updater="nesterovs", momentum=0.9).list(
        DenseLayer(n_in=4, n_out=8, activation="tanh"),
        OutputLayer(n_out=2, activation="softmax",
                    loss_function="mcxent"))


def _pool():
    import numpy as np
    rng = np.random.default_rng(7)
    xs = rng.normal(size=(3, 16, 4)).astype(np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (3, 16))]
    return xs, ys


def _fit_batched_job():
    """Sharded scanned fit over the GLOBAL 2-process mesh."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from tests.test_multihost import _mlp_conf, _pool

    net = MultiLayerNetwork(_mlp_conf()).init()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    xs, ys = _pool()
    scores = np.asarray(pw.fit_batched(xs, ys, epochs=2))
    return {"process": jax.process_index(),
            "scores": scores.tolist(),
            "params": np.asarray(net.params_flat()).tolist()}


@pytest.mark.slow
def test_two_process_sharded_fit_matches_single_process():
    """The true TestCompareParameterAveragingSparkVsSingleMachine analog
    ACROSS A PROCESS BOUNDARY (VERDICT r1 #7): a 2-process global-mesh
    scanned fit must equal the plain single-process fit bit-for-bit
    (same pool, same updater)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    launcher = MultiHostLauncher(num_processes=2, devices_per_process=2)
    results = launcher.run(_fit_batched_job, timeout=240)
    assert len(results) == 2

    single = MultiLayerNetwork(_mlp_conf()).init()
    xs, ys = _pool()
    s_scores = np.asarray(single.fit_batched(xs, ys, epochs=2))
    s_params = np.asarray(single.params_flat())
    for r in results:
        np.testing.assert_allclose(np.asarray(r["scores"]), s_scores,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(r["params"]), s_params,
                                   rtol=1e-4, atol=1e-5)


def _steps_data():
    import numpy as np
    rng = np.random.default_rng(3)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    return x, y


def _crash_after_ckpt_job():
    """3 fits on the global mesh → process 0 checkpoints → process 1
    'host-fails' (os._exit) — the surviving process must still finish.
    Results are self-written + os._exit so no process blocks on the
    distributed-runtime exit barrier with a dead peer."""
    import os
    import pickle
    import sys

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.util.checkpointing import CheckpointManager
    from tests.test_multihost import _mlp_conf, _steps_data

    net = MultiLayerNetwork(_mlp_conf()).init()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    x, y = _steps_data()
    for _ in range(3):
        pw.fit(x, y)
    import time as _time

    saved_flag = os.environ["DL4JTPU_TEST_CKPT"] + ".saved"
    dying_flag = os.environ["DL4JTPU_TEST_CKPT"] + ".dying"
    if jax.process_index() == 0:
        mgr = CheckpointManager(os.environ["DL4JTPU_TEST_CKPT"],
                                use_orbax=False)
        mgr.save(net, step=3)
        with open(saved_flag, "w") as f:
            f.write("saved")
        # hold the coordinator alive until the failing host has died —
        # a dying coordinator would abort the peer from the outside,
        # masking the rc=17 'host failure' this test stages
        for _ in range(1200):
            if os.path.exists(dying_flag):
                break
            _time.sleep(0.1)
        _time.sleep(1.0)
        with open(sys.argv[2], "wb") as f:
            pickle.dump({"saved": 3}, f)
        os._exit(0)
    # the failing host waits for the checkpoint flag so the 'failure'
    # is deterministically ordered after the save (collectives are done)
    for _ in range(1200):
        if os.path.exists(saved_flag):
            break
        _time.sleep(0.1)
    with open(dying_flag, "w") as f:
        f.write("dying")
    os._exit(17)  # simulated host failure AFTER the checkpoint


def _resume_job():
    """Restarted cluster: restore the distributed checkpoint, resume the
    remaining 3 steps."""
    import os

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.util.checkpointing import CheckpointManager
    from tests.test_multihost import _mlp_conf, _steps_data

    net = MultiLayerNetwork(_mlp_conf()).init()
    mgr = CheckpointManager(os.environ["DL4JTPU_TEST_CKPT"],
                            use_orbax=False)
    step = mgr.restore(net)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    x, y = _steps_data()
    for _ in range(3):
        pw.fit(x, y)
    return {"process": jax.process_index(), "restored_step": step,
            "params": np.asarray(net.params_flat()).tolist()}


def _uninterrupted_job():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from tests.test_multihost import _mlp_conf, _steps_data

    net = MultiLayerNetwork(_mlp_conf()).init()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    x, y = _steps_data()
    for _ in range(6):
        pw.fit(x, y)
    return {"process": jax.process_index(),
            "params": np.asarray(net.params_flat()).tolist()}


@pytest.mark.slow
def test_kill_process_checkpoint_restart_resume(tmp_path, monkeypatch):
    """End-to-end §5.3/§5.4 story across a REAL process boundary
    (VERDICT r1 #7): train → checkpoint → one host dies (detected as a
    failed launch) → restart the cluster → restore → resume → final
    params equal the uninterrupted run."""
    import os

    monkeypatch.setenv("DL4JTPU_TEST_CKPT", str(tmp_path / "ckpt"))

    launcher = MultiHostLauncher(num_processes=2, devices_per_process=2)
    with pytest.raises(RuntimeError, match="rc=17"):
        launcher.run(_crash_after_ckpt_job, timeout=240)
    # the failure was detected AND the checkpoint survived
    assert (tmp_path / "ckpt" / "step_3").exists()

    resumed = MultiHostLauncher(
        num_processes=2, devices_per_process=2).run(_resume_job,
                                                    timeout=240)
    reference = MultiHostLauncher(
        num_processes=2, devices_per_process=2).run(_uninterrupted_job,
                                                    timeout=240)
    assert all(r["restored_step"] == 3 for r in resumed)
    p_res = np.asarray(resumed[0]["params"])
    p_ref = np.asarray(reference[0]["params"])
    np.testing.assert_allclose(p_res, p_ref, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# VERDICT r4 #3: a TENSOR-PARALLEL axis spanning the process boundary.
# Until round 5 every multi-host test was pure data parallelism; these run
# the megatron composite step with 'model' (and separately 'pipe') laid
# across the two processes, so the per-layer f/g psums (resp. the microbatch
# ppermute hops) ride the DCN transport — the flagship's actual topology.
# ---------------------------------------------------------------------------

def _megatron_cfg_data():
    import numpy as np

    from deeplearning4j_tpu.models.transformer import TransformerConfig
    cfg = TransformerConfig(vocab_size=50, d_model=32, n_heads=4,
                            n_layers=4, max_len=32)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 50, (8, 32)).astype(np.int32)
    tgts = np.roll(toks, -1, 1).astype(np.int32)
    return cfg, toks, tgts


def _run_megatron_on(mesh_arr_5d, schedule="gpipe"):
    """Shared job body: 2 megatron train steps over the given 5-axis
    device array, params gathered back replicated."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.models.transformer import init_params
    from deeplearning4j_tpu.parallel.megatron import (
        init_adam_state, make_parallel_train_step, shard_params)
    from deeplearning4j_tpu.parallel.mesh import AXES
    from tests.test_multihost import _megatron_cfg_data

    cfg, toks, tgts = _megatron_cfg_data()
    mesh = Mesh(mesh_arr_5d, AXES)
    step = make_parallel_train_step(cfg, mesh, learning_rate=1e-2,
                                    pipeline_schedule=schedule)
    ps = shard_params(init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
    st = init_adam_state(ps)
    dspec = NamedSharding(mesh, P(("data",), ("seq",)))
    tok_g = jax.device_put(jnp.asarray(toks), dspec)
    tgt_g = jax.device_put(jnp.asarray(tgts), dspec)
    loss = None
    for _ in range(2):
        ps, st, loss = step(ps, st, tok_g, tgt_g)
    # gather shards to fully-replicated so each host can np.asarray
    gather = jax.jit(lambda t: t,
                     out_shardings=NamedSharding(mesh, P()))
    host = jax.tree_util.tree_map(np.asarray, gather(ps))
    return float(loss), host


def _tp_span_job():
    """'model' axis ACROSS the 2 processes: global devices [p0d0, p0d1,
    p1d0, p1d1] arranged so each data rank's model pair is (p0dX, p1dX)
    — every attention/MLP output psum crosses the process boundary."""
    import jax
    import numpy as np

    from tests.test_multihost import _run_megatron_on

    devs = np.array(jax.devices())
    arr = devs.reshape(2, 2).T          # [data, model]
    spans = len({d.process_index for d in arr[0]}) == 2
    loss, host = _run_megatron_on(arr.reshape(1, 2, 1, 2, 1))
    return {"process": jax.process_index(), "model_spans_procs": spans,
            "loss": loss, "params": host}


def _pp_span_job():
    """'pipe' axis ACROSS the 2 processes ('model' within each), under
    the 1F1B schedule: activation/cotangent ppermute hops cross DCN."""
    import jax
    import numpy as np

    from tests.test_multihost import _run_megatron_on

    devs = np.array(jax.devices())
    arr = devs.reshape(2, 2)            # [pipe, model]
    spans = len({d.process_index for d in arr[:, 0]}) == 2
    loss, host = _run_megatron_on(arr.reshape(2, 1, 1, 2, 1),
                                  schedule="1f1b")
    return {"process": jax.process_index(), "pipe_spans_procs": spans,
            "loss": loss, "params": host}


def _sp_span_job():
    """'seq' axis ACROSS the 2 processes ('model' within each): ring
    attention's per-block K/V ppermute hops — and the loss psum over
    ('data','seq') — ride the DCN transport. Closes VERDICT r5 weak
    #3: 'seq' was the only mesh axis with no cross-process evidence,
    and it is the designated path past T=8192."""
    import jax
    import numpy as np

    from tests.test_multihost import _run_megatron_on

    devs = np.array(jax.devices())
    arr = devs.reshape(2, 2)            # [seq, model]
    spans = len({d.process_index for d in arr[:, 0]}) == 2
    loss, host = _run_megatron_on(arr.reshape(1, 1, 2, 2, 1))
    return {"process": jax.process_index(), "seq_spans_procs": spans,
            "loss": loss, "params": host}


def _single_device_reference():
    """Single-device megatron run in the test process (CPU mesh)."""
    import jax

    from deeplearning4j_tpu.models.transformer import init_params
    from deeplearning4j_tpu.parallel.megatron import (
        init_adam_state, make_parallel_train_step, shard_params)
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg, toks, tgts = _megatron_cfg_data()
    mesh = make_mesh(MeshSpec())
    step = make_parallel_train_step(cfg, mesh, learning_rate=1e-2)
    ps = shard_params(init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
    st = init_adam_state(ps)
    loss = None
    for _ in range(2):
        ps, st, loss = step(ps, st, toks, tgts)
    return float(loss), jax.tree_util.tree_map(np.asarray, ps)


def _assert_matches_single(results, span_key):
    import jax

    ref_loss, ref_params = _single_device_reference()
    assert len(results) == 2
    for r in results:
        assert r[span_key], "axis did not span the process boundary"
        assert abs(r["loss"] - ref_loss) < 1e-4
        for a, b in zip(jax.tree_util.tree_leaves(ref_params),
                        jax.tree_util.tree_leaves(r["params"])):
            np.testing.assert_allclose(a, b, atol=5e-4)


@pytest.mark.slow
def test_megatron_tp_axis_across_process_boundary(devices8):
    """TP x DP with 'model' spanning 2 real processes == single-device
    training (loss + every param leaf)."""
    results = MultiHostLauncher(2, 2).run(_tp_span_job, timeout=240)
    _assert_matches_single(results, "model_spans_procs")


@pytest.mark.slow
def test_megatron_pp_1f1b_across_process_boundary(devices8):
    """PP(1F1B) x TP with 'pipe' spanning 2 real processes ==
    single-device training."""
    results = MultiHostLauncher(2, 2).run(_pp_span_job, timeout=240)
    _assert_matches_single(results, "pipe_spans_procs")


@pytest.mark.slow
def test_ring_sp_axis_across_process_boundary(devices8):
    """SP(ring) x TP with 'seq' spanning 2 real processes ==
    single-device training (loss + every param leaf) — the last mesh
    axis to get cross-process evidence."""
    results = MultiHostLauncher(2, 2).run(_sp_span_job, timeout=240)
    _assert_matches_single(results, "seq_spans_procs")
