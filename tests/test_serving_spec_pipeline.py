"""Schedule-ahead speculative decoding (ISSUE-19 tentpole).

Speculative decoding now COMPOSES with the double-buffered tick loop
instead of falling back: the pipelined scheduler dispatches tick N+1
against a worst-case K+1-token reservation per speculating slot
(rem/budget masks treat the reservation as spent; paged COW privatizes
the full window) and the commit boundary reconciles actual acceptance
— refunding the unaccepted remainder and pricing it in
serving_spec_schedule_waste_tokens_total. Proven deterministically on
CPU:

- EXACTNESS under schedule-ahead: the pipelined speculative engine is
  TOKEN-IDENTICAL to the synchronous speculative engine (itself proven
  identical to plain decode in test_serving_spec.py) across a 3-seed
  sampled sweep — float AND int8 KV, contiguous AND paged, imperfect
  early-exit drafters, prefix-hit admissions mid-stream;
- host-sync discipline survives speculation: at most ONE blocking
  device->host sync per tick, same as non-speculative pipelining;
- the adaptive-K controller still walks a CLOSED program set (no
  steady-state recompiles) even though K changes land one tick late
  (they are decided at commit, applied at the next dispatch);
- schedule waste is observable and honest: a full-acceptance
  budget-aligned run wastes ZERO reserved tokens; an imperfect drafter
  wastes > 0; the series never exists on sync-spec or spec-off
  engines;
- forensics: a poisoned draft round in flight when a SYNC-time device
  failure lands never corrupts the committed prefix — recovery
  restores the last committed snapshot, every surviving token is a
  prefix of the clean stream, and isolation completes the requests
  token-exactly.
"""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.failure import ServingFaultInjector
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (EngineConfig, InferenceEngine,
                                        RequestStatus)
from deeplearning4j_tpu.serving.engine import (_compiled_paged_spec_decode,
                                               _compiled_spec_decode)
from helpers import assert_no_recompiles

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


def _config(pipeline, **kw):
    base = dict(max_new_tokens=11, backoff_base_s=0.0,
                spec_decode=True, spec_k=4, draft="self",
                pipeline=pipeline)
    base.update(kw)
    return EngineConfig(**base)


def _run(params, mesh, pipeline, prompts, max_new=11, **kw):
    eng = InferenceEngine(CFG, mesh, params, _config(pipeline, **kw))
    assert eng._pipe is pipeline           # spec no longer falls back
    hs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run_pending()
    return eng, [h.result(0) for h in hs]


# ---------------------------------------------------------------------------
# exactness: pipelined spec == sync spec, everywhere
# ---------------------------------------------------------------------------

# contiguous/paged x float/int8-KV; spec_k=2 keeps the adaptive-K
# program ladder short (K in {2, 1}) so the sweep stays cheap
MATRIX = [
    dict(),
    dict(kv_quantize="int8"),
    dict(paged=True, page_size=8),
    dict(paged=True, page_size=8, kv_quantize="int8"),
]


@pytest.mark.parametrize("kw", MATRIX,
                         ids=["contig-f32", "contig-int8",
                              "paged-f32", "paged-int8"])
def test_sampled_sweep_pipelined_equals_sync(params, mesh1, kw):
    """The tentpole exactness claim: an early-exit drafter (genuine
    mid-window rejections) under temperature/top-k sampling produces
    byte-identical streams whether speculation runs synchronously or
    one tick ahead — across 3 seeds, because the reservation only
    moves ROUND boundaries (rem masks are conservative) while token
    values stay position-keyed."""
    for seed in (0, 1, 2):
        prompts = [_prompt(8, seed), _prompt(6, seed + 3)]
        sample = dict(draft="layers:1", spec_k=2, temperature=0.9,
                      top_k=5, seed=seed, **kw)
        _, want = _run(params, mesh1, False, prompts, **sample)
        _, got = _run(params, mesh1, True, prompts, **sample)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)


def test_prefix_hit_admission_mid_stream_stays_exact(params, mesh1):
    """A second request admitted MID-PIPELINE onto a cached prefix
    chain (COW-shared pages) decodes bit-identically to the sync spec
    engine — the K+1 reservation privatizes the full worst-case
    window, so the sharer's pages are never perturbed even when the
    in-flight round is later truncated by rejection."""
    sysp = (np.arange(16, dtype=np.int32) * 5) % CFG.vocab_size
    pa = np.concatenate([sysp, np.array([1, 2], np.int32)])
    pb = np.concatenate([sysp, np.array([3, 4], np.int32)])

    def staggered(pipeline):
        eng = InferenceEngine(
            CFG, mesh1, params,
            _config(pipeline, draft="layers:1", spec_k=3,
                    max_new_tokens=8, paged=True, page_size=8,
                    max_batch_size=2))
        ha = eng.submit(pa, max_new_tokens=8)
        eng.tick()                       # A is decoding when B lands
        hb = eng.submit(pb, max_new_tokens=8)
        eng.run_pending()
        hits = eng.registry.get("serving_prefix_cache_hits")
        return ha.result(0), hb.result(0), int(hits._unlabeled().value)

    wa, wb, _ = staggered(False)
    ga, gb, hits = staggered(True)
    assert hits >= 1                     # B actually shared the prefix
    np.testing.assert_array_equal(ga, wa)
    np.testing.assert_array_equal(gb, wb)


# ---------------------------------------------------------------------------
# host-sync discipline + compile discipline
# ---------------------------------------------------------------------------

def test_at_most_one_blocking_sync_per_tick_with_spec(params, mesh1):
    """The ISSUE-12 sync discipline survives speculation: every tick
    of the pipelined speculative engine blocks on the device at most
    once (the previous tick's commit) — the draft+verify round rides
    the same async dispatch as plain decode."""
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(True, draft="layers:1"))
    for s in range(4):
        eng.submit(_prompt(8, s))
    deltas = []
    while True:
        s0 = eng._syncs_total
        if not eng.tick():
            break
        deltas.append(eng._syncs_total - s0)
    assert deltas and max(deltas) <= 1, \
        f"pipelined spec engine synced {max(deltas)}x in one tick"


def test_steady_state_walks_a_closed_program_set(params, mesh1):
    """After a first wave warms the adaptive-K ladder, a second wave
    of pipelined speculative traffic compiles NOTHING new — commit-lag
    K updates reuse the same programs the sync engine compiled."""
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(True, draft="layers:1"))
    hs = [eng.submit(_prompt(8, s)) for s in range(3)]
    eng.run_pending()
    assert all(h.status == RequestStatus.COMPLETED for h in hs)
    with assert_no_recompiles(_compiled_spec_decode):
        hs = [eng.submit(_prompt(8, 10 + s)) for s in range(3)]
        eng.run_pending()
    assert all(h.status == RequestStatus.COMPLETED for h in hs)


# ---------------------------------------------------------------------------
# schedule waste accounting
# ---------------------------------------------------------------------------

def test_schedule_waste_zero_on_full_acceptance(params, mesh1):
    """draft='self' + greedy accepts every proposal and
    max_new_tokens=11 aligns the budget to whole K+1 windows, so the
    worst-case reservation is ALWAYS exactly consumed: the waste
    counter exists (pipelined spec engine) but stays at zero."""
    eng, _ = _run(params, mesh1, True, [_prompt()])
    fam = eng.registry.get("serving_spec_schedule_waste_tokens")
    assert fam is not None
    assert fam._unlabeled().value == 0


def test_schedule_waste_prices_rejected_windows(params, mesh1):
    """An imperfect drafter rejects mid-window, so commits reconcile
    below the K+1 reservation — the refunded tokens are priced in
    serving_spec_schedule_waste_tokens_total. Sync-spec and spec-off
    engines never register the series (their scrapes are
    byte-unchanged)."""
    eng, _ = _run(params, mesh1, True, [_prompt(8, s) for s in range(3)],
                  draft="layers:1", temperature=0.9, top_k=5, seed=0)
    fam = eng.registry.get("serving_spec_schedule_waste_tokens")
    assert fam is not None and fam._unlabeled().value > 0

    sync_eng, _ = _run(params, mesh1, False, [_prompt()])
    assert sync_eng.registry.get(
        "serving_spec_schedule_waste_tokens") is None

    plain = InferenceEngine(CFG, mesh1, params,
                            EngineConfig(max_new_tokens=8,
                                         backoff_base_s=0.0))
    assert plain._pipe is True
    assert plain.registry.get(
        "serving_spec_schedule_waste_tokens") is None


# ---------------------------------------------------------------------------
# forensics: poisoned draft in flight + sync-time failure
# ---------------------------------------------------------------------------

def test_poison_mid_pipeline_committed_prefix_stays_clean(params,
                                                          mesh1):
    """The compound failure the schedule-ahead design must survive: a
    POISONED draft round is dispatched (in flight, uncommitted) when
    the previous round's SYNC fails. _recover_failed_tick restores the
    last committed snapshot and drops the poisoned dispatch — so every
    request's committed prefix is provably a prefix of the clean
    stream, and isolation finishes the runs token-exactly."""
    prompts = [_prompt(6, s) for s in range(3)]
    _, want = _run(params, mesh1, False, prompts)

    # poison rid 1's draft pass at step 2 (the second spec round): at
    # that moment the pipeline holds a committed prefill prefix, round
    # 1 in flight, and the poisoned round being dispatched — the sync
    # failure then lands on round 1's commit, inside the same tick
    inj = ServingFaultInjector(draft_poison_at={2: 1})
    eng = InferenceEngine(CFG, mesh1, params, _config(True),
                          fault_injector=inj)
    orig = eng._block_on_many
    fired = []

    def flaky(xs):
        if not fired and inj.drafts_poisoned:
            fired.append(True)
            raise RuntimeError("injected sync failure under poison")
        return orig(xs)

    eng._block_on_many = flaky
    hs = [eng.submit(p, max_new_tokens=11) for p in prompts]
    while not fired and eng.tick():
        pass
    assert fired, "the poisoned-tick sync failure never fired"

    # forensics: whatever survived recovery is a clean prefix
    for h, w in zip(hs, want):
        g = h.generated
        np.testing.assert_array_equal(
            g, w[len(h.prompt):len(h.prompt) + g.shape[0]])
    assert not eng._pending              # in-flight dispatch dropped

    eng.run_pending()                    # isolation completes them
    for h, w in zip(hs, want):
        np.testing.assert_array_equal(h.result(0), w)
    assert eng.stats["preempted"] > 0
