"""Quantized inference subsystem (ISSUE-5): accuracy + integration.

The tentpole guarantees, each proven deterministically on the CPU
backend:

- quantize/dequantize round-trip error stays inside the symmetric
  absmax bound (half a quantization step per element, per channel);
- a quantized tree is a DROP-IN params argument: forward /
  forward_hidden / generate run unchanged, with bounded
  max-logit-divergence vs float32 on a tiny transformer;
- int8-KV continuous decode is token-faithful vs the float KV path
  (sharpened-logit harness: quantization noise must not flip greedy
  argmax when logit gaps dominate the error bound);
- `quantize=None` stays BIT-IDENTICAL to the pre-quantization engine
  (the regression gate: the refactor cannot perturb the default path);
- the engine's HBM accounting (param_bytes / kv_bytes_per_slot)
  records the >= 40% reduction the ISSUE's acceptance bar demands;
- fp8 degrades to int8 on CPU (`resolve_mode`) and the subsystem
  imports cleanly without fp8 support;
- hot reload re-quantizes: a float checkpoint restored into a
  quantized engine comes back as a quantized tree.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   generate, forward,
                                                   init_cache,
                                                   init_params, prefill)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.quant.core import (QuantizedTensor, dequantize,
                                           fake_quant, fp8_supported,
                                           quantize, quantized_matmul,
                                           resolve_mode)
from deeplearning4j_tpu.quant.kv import (init_quant_slot_state,
                                         quantize_rows,
                                         slot_pool_bytes)
from deeplearning4j_tpu.quant.model import (dequantize_params,
                                            max_logit_divergence,
                                            param_bytes,
                                            quantize_params)
from deeplearning4j_tpu.serving import EngineConfig, InferenceEngine

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def sharp_params(params):
    """Sharpened-logit harness: scaling Wout multiplies every logit
    GAP, so greedy argmax has margin >> the quantization error bound
    and token-fidelity tests assert exact equality instead of a
    flaky match fraction."""
    p = dict(params)
    p["Wout"] = params["Wout"] * 4.0
    return p


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


# ---------------------------------------------------------------------------
# core: round-trip error bounds, pytree behavior, capability fallback
# ---------------------------------------------------------------------------

def test_quantize_roundtrip_error_bound():
    """Symmetric absmax int8: |x - deq(q(x))| <= scale/2 elementwise,
    where scale is the CHANNEL's own step — per-channel scaling keeps
    small-range channels accurate next to big-range ones."""
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 48))
    x = x * (1.0 + 99.0 * (jnp.arange(48) == 7))    # one hot channel
    qt = quantize(x, axis=-2)
    err = jnp.abs(dequantize(qt) - x)
    assert float(jnp.max(err - qt.scales / 2.0)) <= 1e-6
    # the hot channel must not have stretched its neighbors' grids
    cold = jnp.max(err[:, :7])
    assert float(cold) <= float(jnp.max(jnp.abs(x[:, :7]))) / 254 + 1e-6


def test_fake_quant_and_quantized_matmul():
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 16))
    w = jax.random.normal(jax.random.PRNGKey(3), (16, 4))
    qt = quantize(w, axis=-2)
    np.testing.assert_allclose(np.asarray(fake_quant(w)),
                               np.asarray(dequantize(qt)), atol=0)
    ref = x @ dequantize(qt, x.dtype)
    np.testing.assert_allclose(np.asarray(quantized_matmul(x, qt)),
                               np.asarray(ref), atol=0)
    # plain arrays pass through quantized_matmul unchanged
    np.testing.assert_allclose(np.asarray(quantized_matmul(x, w)),
                               np.asarray(x @ w), atol=1e-6)


def test_quantized_tensor_pytree_and_indexing():
    w = jax.random.normal(jax.random.PRNGKey(4), (3, 8, 5))
    qt = quantize(w, axis=-2)
    assert qt.shape == (3, 8, 5) and qt.scales.shape == (3, 1, 5)
    leaves = jax.tree_util.tree_leaves(qt)
    assert [l.shape for l in leaves] == [(3, 8, 5), (3, 1, 5)]
    sl = qt[1]
    assert isinstance(sl, QuantizedTensor)
    assert sl.shape == (8, 5) and sl.scales.shape == (1, 5)
    # scan over the leading axis slices values+scales in lockstep
    def body(c, q):
        return c + jnp.sum(q.astype(jnp.float32)), None
    tot, _ = jax.lax.scan(body, 0.0, qt)
    np.testing.assert_allclose(float(tot),
                               float(jnp.sum(dequantize(qt))),
                               rtol=1e-5)


def test_fp8_resolves_to_int8_on_cpu():
    """The capability check: CPU has no hardware fp8, so "fp8"
    degrades to int8 everywhere (core, params, engine) instead of
    failing or limping through emulation."""
    if fp8_supported():
        pytest.skip("backend has fp8; fallback not exercised")
    assert resolve_mode("fp8") == "int8"
    assert resolve_mode("int8") == "int8"
    assert resolve_mode(None) is None
    w = jax.random.normal(jax.random.PRNGKey(5), (8, 4))
    assert quantize(w, mode="fp8").values.dtype == jnp.int8
    with pytest.raises(ValueError, match="unknown quantization mode"):
        resolve_mode("int4")


def test_quant_import_smoke_subprocess():
    """Graft-entry-style smoke: a FRESH interpreter (no conftest
    bootstrap) imports the quant subsystem cleanly and resolves modes
    without optional fp8 support — the driver-invocation-shaped
    guard. XLA_FLAGS is stripped (conftest mutates it in this
    process); the child self-bootstraps a CPU mesh the same way
    dryrun_multichip does."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import _force_virtual_cpu_mesh; "
         "_force_virtual_cpu_mesh(2); "
         "import deeplearning4j_tpu.quant as q; "
         "assert q.resolve_mode('int8') == 'int8'; "
         "assert q.resolve_mode('fp8') in ('int8', 'fp8'); "
         "print('QUANT_OK')"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "QUANT_OK" in proc.stdout


def test_quant_subsystem_imports_cleanly_without_fp8():
    """Smoke (in-process): the package import must not require
    optional fp8 support — public API present, modes resolvable."""
    import deeplearning4j_tpu.quant as q
    for name in ("QuantizedTensor", "quantize", "dequantize",
                 "fake_quant", "quantized_matmul", "resolve_mode",
                 "fp8_supported", "quantize_params", "param_bytes",
                 "init_quant_slot_state", "quantize_rows",
                 "slot_pool_bytes"):
        assert hasattr(q, name), name
    assert q.resolve_mode("fp8") in ("int8", "fp8")


# ---------------------------------------------------------------------------
# model trees: structure, accuracy, drop-in forward
# ---------------------------------------------------------------------------

def test_quantize_params_structure(params):
    qp = quantize_params(params)
    assert isinstance(qp["embed"], QuantizedTensor)
    assert isinstance(qp["Wout"], QuantizedTensor)
    for name in ("Wq", "Wk", "Wv", "Wo", "W1", "W2"):
        assert isinstance(qp["blocks"][name], QuantizedTensor), name
    # numerically fragile leaves stay floating-point, unquantized
    for name in ("pos", "lnfg", "lnfb"):
        assert not isinstance(qp[name], QuantizedTensor)
        assert jnp.issubdtype(qp[name].dtype, jnp.floating)
    for name in ("ln1g", "ln1b", "ln2g", "ln2b", "b1", "b2"):
        assert not isinstance(qp["blocks"][name], QuantizedTensor)
    # per-output-channel layout: stacked [L, in, out] -> [L, 1, out]
    assert qp["blocks"]["Wq"].scales.shape == (CFG.n_layers, 1,
                                               CFG.d_model)
    assert qp["embed"].scales.shape == (CFG.vocab_size, 1)
    with pytest.raises(ValueError, match="already quantized"):
        quantize_params(qp)
    # dequantized tree approximates the original
    dq = dequantize_params(qp)
    err = jnp.max(jnp.abs(dq["blocks"]["Wq"]
                          - params["blocks"]["Wq"]))
    assert float(err) <= float(jnp.max(
        qp["blocks"]["Wq"].scales)) / 2 + 1e-6
    assert param_bytes(qp) < 0.5 * param_bytes(params)


def test_quantized_forward_max_logit_divergence(params):
    """A quantized tree is a drop-in `params` for forward(); the
    max-logit divergence vs float32 stays under a stated bound on the
    tiny harness (observed ~0.05; bound leaves slack for cross-version
    numeric drift)."""
    toks = jnp.asarray(np.stack([_prompt(16, s) for s in range(4)]))
    qp = quantize_params(params)
    div = max_logit_divergence(CFG, params, qp, toks)
    assert div <= 0.25, div
    # MoE config too: router stays float, experts dequantize on the fly
    moe_cfg = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                                n_layers=2, max_len=64, n_experts=4)
    moe_params = init_params(moe_cfg, jax.random.PRNGKey(0))
    moe_div = max_logit_divergence(moe_cfg, moe_params,
                                   quantize_params(moe_params), toks)
    assert moe_div <= 0.25, moe_div


def test_quantized_generate_runs(params):
    """Single-chip KV-cached sampling accepts a quantized tree."""
    qp = quantize_params(params)
    out = generate(CFG, qp, _prompt()[None], 6, jax.random.PRNGKey(0),
                   temperature=0.0)
    assert out.shape == (1, 14)
    assert int(jnp.min(out)) >= 0 and int(jnp.max(out)) < CFG.vocab_size


# ---------------------------------------------------------------------------
# cache_dtype satellite: bf16 caches under f32 activations
# ---------------------------------------------------------------------------

def test_cache_dtype_passthrough(params):
    ck, cv = init_cache(CFG, 2)
    assert ck.dtype == jnp.float32          # default: activation dtype
    ck, cv = init_cache(CFG, 2, cache_dtype=jnp.bfloat16)
    assert ck.dtype == jnp.bfloat16 and cv.dtype == jnp.bfloat16
    cfg_bf = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                               n_layers=2, max_len=64,
                               cache_dtype="bfloat16")
    assert cfg_bf.cache_jnp_dtype() == jnp.bfloat16
    assert cfg_bf.activation_dtype() == jnp.float32
    ck, _ = init_cache(cfg_bf, 2)
    assert ck.dtype == jnp.bfloat16
    # prefill writes land in the cache dtype; logits stay close to f32
    pr = jnp.asarray(_prompt()[None])
    logits32, caches32 = prefill(CFG, params, pr)
    logits16, caches16 = prefill(cfg_bf, params, pr)
    assert caches16[0].dtype == jnp.bfloat16
    assert caches32[0].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(logits16),
                               np.asarray(logits32), atol=0.1)


def test_slot_pool_bytes_analytic_matches_measured(mesh1):
    state = init_quant_slot_state(CFG, mesh1, 4, "int8")
    measured = sum(int(a.nbytes) for a in state)
    assert slot_pool_bytes(CFG, 4, kv_mode="int8", tp=1) == measured
    from deeplearning4j_tpu.parallel.serving import init_slot_state
    fstate = init_slot_state(CFG, mesh1, 4)
    fmeasured = sum(int(a.nbytes) for a in fstate)
    assert slot_pool_bytes(CFG, 4) == fmeasured
    # the quantized pool is ~4x smaller (scales cost a little back)
    assert measured < 0.35 * fmeasured


def test_quantize_rows_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(6), (3, 5, 16))
    q, s = quantize_rows(x, "int8")
    assert q.dtype == jnp.int8 and s.shape == (3, 5)
    err = jnp.abs(q.astype(jnp.float32) * s[..., None] - x)
    assert float(jnp.max(err - s[..., None] / 2.0)) <= 1e-6
    # zero rows quantize to zero with scale 1 (never divide by zero)
    qz, sz = quantize_rows(jnp.zeros((2, 4)), "int8")
    assert float(jnp.max(jnp.abs(qz.astype(jnp.float32)))) == 0.0
    np.testing.assert_array_equal(np.asarray(sz), np.ones((2,)))


# ---------------------------------------------------------------------------
# engine integration: fidelity, regression, accounting, reload
# ---------------------------------------------------------------------------

def _engine(params, mesh, **kw):
    cfgkw = dict(decode_chunk=2, max_new_tokens=12,
                 backoff_base_s=0.0)
    quant = {k: kw.pop(k) for k in ("quantize", "kv_quantize")
             if k in kw}
    cfgkw.update(kw)
    return InferenceEngine(CFG, mesh, params, EngineConfig(**cfgkw),
                           **quant)


def test_engine_quantize_none_bit_identical(sharp_params, mesh1):
    """THE regression gate: with quantization off, the engine's
    continuous decode must stay bit-identical to single-chip
    `generate` — the quant refactor cannot perturb the default path."""
    eng = _engine(sharp_params, mesh1)
    h = eng.submit(_prompt())
    eng.run_pending()
    ref = np.asarray(generate(CFG, sharp_params, _prompt()[None], 12,
                              jax.random.PRNGKey(0), temperature=0.0))
    np.testing.assert_array_equal(h.result(1), ref[0])


def test_int8_kv_continuous_token_fidelity(sharp_params, mesh1):
    """int8-KV continuous decode (float weights) is token-faithful vs
    the float-KV path on the sharpened harness: per-row absmax error
    (<= 1/254 relative) is far inside the greedy argmax margin, so the
    full continuation must match EXACTLY."""
    ref_eng = _engine(sharp_params, mesh1)
    kv_eng = _engine(sharp_params, mesh1, kv_quantize="int8")
    outs = {}
    for name, eng in (("float", ref_eng), ("int8kv", kv_eng)):
        hs = [eng.submit(_prompt(6, s)) for s in range(3)]
        eng.run_pending()
        outs[name] = [h.result(1) for h in hs]
    for a, b in zip(outs["float"], outs["int8kv"]):
        np.testing.assert_array_equal(a, b)
    hq = kv_eng.health()
    assert hq["kv_quantize"] == "int8"
    # the quantized pool really is the one allocated
    assert len(kv_eng._slot_state) == 6
    assert kv_eng._slot_state[0].dtype == jnp.int8


def test_engine_int8_weights_and_kv_completes(params, mesh1):
    """The full 2x2 corner (int8 weights x int8 KV) serves mixed
    traffic to completion with in-bounds tokens and >= 40% HBM
    reduction on BOTH accounting axes (the ISSUE acceptance bar)."""
    feng = _engine(params, mesh1)
    qeng = _engine(params, mesh1, quantize="int8", kv_quantize="int8")
    hs = [qeng.submit(_prompt(t0, s))
          for s, t0 in enumerate((4, 8, 12))]
    qeng.run_pending()
    for h in hs:
        out = h.result(1)
        assert out.shape[0] >= 4 + 12 - 8
        assert int(out.min()) >= 0 and int(out.max()) < CFG.vocab_size
    fh, qh = feng.health(), qeng.health()
    assert qh["quantize"] == "int8" and qh["kv_quantize"] == "int8"
    assert qh["param_bytes"] <= 0.6 * fh["param_bytes"]
    assert qh["kv_bytes_per_slot"] <= 0.6 * fh["kv_bytes_per_slot"]
    assert qh["kv_pool_bytes"] <= 0.6 * fh["kv_pool_bytes"]
    # the same numbers surface as pull gauges in the registry
    g = qeng.registry.get("serving_param_bytes")
    assert g is not None
    assert int(g.value) == qh["param_bytes"]


def test_quantized_engine_hot_reload_requantizes(params, mesh1,
                                                 tmp_path):
    """reload_weights on a quantized engine restores the FLOAT
    checkpoint against the float template, requantizes, and keeps
    serving quantized — quantize-on-hot-reload."""
    from deeplearning4j_tpu.util.checkpointing import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"), use_orbax=False)
    new_params = init_params(CFG, jax.random.PRNGKey(7))
    mgr.save_tree(new_params, 5)

    eng = _engine(params, mesh1, quantize="int8")
    assert eng.reload_weights(mgr) == 5
    assert eng.health()["weights_step"] == 5
    assert isinstance(eng._params["Wout"], QuantizedTensor)
    h = eng.submit(_prompt())
    eng.run_pending()
    out = h.result(1)
    # served tokens come from the RELOADED weights: they match the
    # quantized-from-scratch tree of the new params
    ref_eng = _engine(new_params, mesh1, quantize="int8")
    h2 = ref_eng.submit(_prompt())
    ref_eng.run_pending()
    np.testing.assert_array_equal(out, h2.result(1))


# ---------------------------------------------------------------------------
# the larger accuracy sweep stays out of tier-1
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_accuracy_sweep_larger_model():
    """Divergence statistics at a serving-shaped geometry: int8
    weights keep max-logit-divergence small relative to the logit
    scale across seeds, and int8-KV greedy decode stays faithful."""
    cfg = TransformerConfig(vocab_size=128, d_model=128, n_heads=8,
                            n_layers=4, max_len=128)
    for seed in range(3):
        p = init_params(cfg, jax.random.PRNGKey(seed))
        toks = jnp.asarray(
            np.stack([(np.arange(64) * (s + 3)) % 128
                      for s in range(4)]).astype(np.int32))
        qp = quantize_params(p)
        lf = forward(cfg, p, toks).astype(jnp.float32)
        div = max_logit_divergence(cfg, p, qp, toks)
        scale = float(jnp.max(jnp.abs(lf)))
        assert div <= 0.1 * max(scale, 1.0), (seed, div, scale)
    mesh = make_mesh(MeshSpec(data=2, model=2))
    p = init_params(cfg, jax.random.PRNGKey(0))
    p = dict(p, Wout=p["Wout"] * 4.0)
    ref = np.asarray(generate(cfg, p, ((np.arange(16) * 3) % 128)[None],
                              32, jax.random.PRNGKey(0),
                              temperature=0.0))[0]
    eng = InferenceEngine(cfg, mesh, p,
                          EngineConfig(decode_chunk=4,
                                       max_new_tokens=32),
                          kv_quantize="int8")
    h = eng.submit((np.arange(16) * 3) % 128)
    eng.run_pending()
    np.testing.assert_array_equal(h.result(1), ref)
