"""Tensor+data-parallel generation == single-chip generation.

The serving analog of the spark-vs-single equivalence proof (SURVEY
§4): greedy decode through parallel/serving.py on a (data x model)
mesh must reproduce models/transformer.generate token-for-token."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   generate, init_params)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.serving import (make_parallel_generate,
                                                 shard_serving_params)


@pytest.fixture
def mesh(devices8):
    return make_mesh(MeshSpec(data=2, model=2))


def test_tp_generate_matches_single_chip(mesh):
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=3, max_len=96)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    key = jax.random.PRNGKey(2)
    want = np.asarray(generate(cfg, params, prompt, max_new_tokens=24,
                               key=key, temperature=0.0))
    pgen = make_parallel_generate(cfg, mesh, max_new_tokens=24,
                                  temperature=0.0)
    got = np.asarray(pgen(shard_serving_params(params, cfg, mesh),
                          prompt, key))
    np.testing.assert_array_equal(got, want)


def test_moe_tp_generate_matches_single_chip(mesh):
    """MoE serving (experts replicated, FFN hidden sharded over
    'model', GLOBAL capacity-drop decisions) == single-chip MoE
    generate token-for-token. capacity_factor chosen so the cap BINDS
    (B=4 tokens/step, E=2, cap=int(0.6*4/2)=1): the global-position
    drop logic is exercised, not just the no-drop happy path."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, max_len=64, n_experts=2,
                            capacity_factor=0.6)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    key = jax.random.PRNGKey(2)
    want = np.asarray(generate(cfg, params, prompt, max_new_tokens=16,
                               key=key, temperature=0.0))
    pgen = make_parallel_generate(cfg, mesh, max_new_tokens=16,
                                  temperature=0.0)
    got = np.asarray(pgen(shard_serving_params(params, cfg, mesh),
                          prompt, key))
    np.testing.assert_array_equal(got, want)


def test_tp_generate_sampled_is_valid(mesh):
    """Sampled decode: valid tokens, deterministic for a fixed key."""
    cfg = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                            n_layers=2, max_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.zeros((4, 8), jnp.int32)
    pgen = make_parallel_generate(cfg, mesh, max_new_tokens=12,
                                  temperature=1.0)
    sp = shard_serving_params(params, cfg, mesh)
    a = np.asarray(pgen(sp, prompt, jax.random.PRNGKey(3)))
    b = np.asarray(pgen(sp, prompt, jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (4, 20)
    assert (a >= 0).all() and (a < 32).all()
    # identical prompts on DIFFERENT data shards must not sample
    # identical continuations (per-shard key fold; rows 0-1 live on
    # data rank 0, rows 2-3 on rank 1)
    assert not np.array_equal(a[:2, 8:], a[2:, 8:])


def test_tp_sampled_filters_match_single_chip(devices8):
    """SAMPLED decode with top-k + nucleus filtering on a TP-only mesh
    (dp=1 — key schedule identical to single-chip by construction) ==
    `generate` token-for-token: same key, same temperature, same
    filters. Logits are replicated on every model rank, so the filter
    + categorical draw must agree exactly (VERDICT r4 weak #5 — the
    greedy tests cannot see a filter gap because greedy ignores it)."""
    mesh = make_mesh(MeshSpec(data=1, model=4))
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=3, max_len=96)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    key = jax.random.PRNGKey(7)
    for top_k, top_p in [(8, 1.0), (0, 0.7), (8, 0.9)]:
        want = np.asarray(generate(cfg, params, prompt,
                                   max_new_tokens=24, key=key,
                                   temperature=0.8, top_k=top_k,
                                   top_p=top_p))
        pgen = make_parallel_generate(cfg, mesh, max_new_tokens=24,
                                      temperature=0.8, top_k=top_k,
                                      top_p=top_p)
        got = np.asarray(pgen(shard_serving_params(params, cfg, mesh),
                              prompt, key))
        np.testing.assert_array_equal(got, want)


def test_tp_generate_rejects_bad_filters(devices8):
    cfg = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                            n_layers=2, max_len=64)
    mesh = make_mesh(MeshSpec(data=2, model=2))
    with pytest.raises(ValueError, match="top_p"):
        make_parallel_generate(cfg, mesh, max_new_tokens=4,
                               temperature=1.0, top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        make_parallel_generate(cfg, mesh, max_new_tokens=4,
                               temperature=1.0, top_k=-1)


@pytest.mark.slow
def test_flagship_geometry_serving_smoke(mesh):
    """Serving at the FLAGSHIP geometry (12L/512d/8H, max_len=2048) on
    the CPU mesh — tiny-shape tests can miss shape-dependent sharding
    bugs (VERDICT r3 #8); this pins the real layer count, width and
    cache length end-to-end with check_rep ON, and cross-checks the
    first greedy tokens against single-chip generate."""
    cfg = TransformerConfig(vocab_size=256, d_model=512, n_heads=8,
                            n_layers=12, max_len=2048)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    key = jax.random.PRNGKey(2)
    want = np.asarray(generate(cfg, params, prompt, max_new_tokens=4,
                               key=key, temperature=0.0))
    pgen = make_parallel_generate(cfg, mesh, max_new_tokens=4,
                                  temperature=0.0)
    got = np.asarray(pgen(shard_serving_params(params, cfg, mesh),
                          prompt, key))
    np.testing.assert_array_equal(got, want)


def test_tp_generate_rejects_bad_meshes_and_lengths(devices8):
    cfg = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                            n_layers=2, max_len=16)
    with pytest.raises(ValueError, match="pipe"):
        make_parallel_generate(cfg, make_mesh(MeshSpec(pipe=2, model=2,
                                                       data=2)),
                               max_new_tokens=4)
    mesh = make_mesh(MeshSpec(data=2, model=2))
    pgen = make_parallel_generate(cfg, mesh, max_new_tokens=12)
    params = shard_serving_params(init_params(cfg, jax.random.PRNGKey(0)),
                                  cfg, mesh)
    with pytest.raises(ValueError, match="exceeds"):
        pgen(params, jnp.zeros((4, 8), jnp.int32), jax.random.PRNGKey(1))
