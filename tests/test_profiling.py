"""Continuous profiling & cost attribution (ISSUE-15).

The accounting layer's contracts, each proven deterministically on
CPU:

- **Exactness.** XLA's cost analysis of an analytic MLP matches the
  closed-form FLOP count, and `profiling.cost_from_compiled` agrees
  with `util/flops.cost_analysis` (one compiler, one number). The
  engine's per-program cost table holds exactly the analysis of the
  programs it resolved; per-tenant fleet cost totals are exact — the
  sum of per-request bills (terminal trace events) equals the
  federated per-tenant counters across a 2-replica, 3-tenant run.
- **Zero-cost paths.** A prefix-cache hit bills only the recomputed
  suffix tokens; a migrated cache chain adopted at seating bills only
  the private tail — cached compute is free for the tenant exactly as
  it is free for the engine (round-19 serving_prefill_tokens_total
  semantics).
- **Cardinality.** A hostile stream of distinct tenant ids folds into
  "other" past the top-N bound — the scrape stays inside
  `federation.check_cardinality`'s budget no matter the traffic.
- **Cache-warm cost tables.** A compile-cache-warm restart (zero jit
  compiles, every program an AOT load) still reports a COMPLETE cost
  table: the analysis is persisted beside the cached executable, and
  pre-meta (round 17-19) entries degrade to a lazy recompute from the
  loaded executable — never a cache miss.
- **Attribution + capture.** Tick-attributed device seconds sum to
  the engine's busy total; rooflines classify against injected peaks;
  `/profilez` is single-flight and 503s when unsupported.
"""
import json
import time
import urllib.request
import urllib.error

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.observability import MetricsServer
from deeplearning4j_tpu.observability.export import (json_snapshot,
                                                     prometheus_text)
from deeplearning4j_tpu.observability.federation import (
    check_cardinality)
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.observability.profiling import (
    EngineProfiler, NULL_PROFILER, ProfileCapture, TenantMeter,
    cost_from_compiled, roofline)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (EngineConfig, InferenceEngine,
                                        Router)
from deeplearning4j_tpu.util import flops as util_flops

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


# ---------------------------------------------------------------------------
# exactness: closed-form MLP vs the compiler's cost model
# ---------------------------------------------------------------------------

def test_cost_analysis_matches_closed_form_mlp():
    """An analytic two-layer MLP whose FLOPs are known in closed form
    (2*m*k*n per dense matmul): XLA's cost model, read through BOTH
    `util/flops.cost_analysis` (the training path) and
    `profiling.cost_from_compiled` (the serving path), reports exactly
    that number."""
    m, k, n, p = 8, 32, 16, 4
    w1 = np.zeros((k, n), np.float32)
    w2 = np.zeros((n, p), np.float32)
    x = np.zeros((m, k), np.float32)

    fn = jax.jit(lambda x, w1, w2: (x @ w1) @ w2)
    closed_form = 2 * m * k * n + 2 * m * n * p

    via_util = util_flops.cost_analysis(fn, x, w1, w2)
    assert via_util.get("flops") == closed_form

    exe = fn.lower(x, w1, w2).compile()
    via_profiling = cost_from_compiled(exe)
    assert via_profiling["flops"] == closed_form
    assert via_profiling["bytes"] > 0


def test_engine_cost_table_matches_util_flops(params, mesh1):
    """The engine's per-program table holds exactly what
    util/flops-style lower+compile cost analysis reports for the SAME
    geometry — the serving accounting and the training MFU path can
    never disagree about one program's cost."""
    eng = InferenceEngine(CFG, mesh1, params,
                          EngineConfig(decode_chunk=2,
                                       max_new_tokens=6, num_slots=2))
    h = eng.submit(_prompt(), tenant="t0")
    eng.run_pending()
    assert h.done()
    programs = eng.profiler.program_report()
    assert "decode" in programs
    # independently lower+compile the same decode geometry and compare
    from dataclasses import astuple
    from deeplearning4j_tpu.serving.engine import _compiled_decode_chunk
    fargs = (astuple(CFG), mesh1, eng._chunk, eng._num_slots,
             float(eng.config.temperature), int(eng.config.top_k),
             float(eng.config.top_p))
    fn = _compiled_decode_chunk(*fargs)
    eng._ensure_state()
    active = np.zeros((eng._num_slots,), bool)
    rem = np.zeros((eng._num_slots,), np.int32)
    ref = util_flops.cost_analysis(
        fn, eng._params, *eng._slot_state, active, rem,
        eng._root_key())
    assert programs["decode"]["flops_per_invocation"] == \
        ref.get("flops")
    assert programs["decode"]["tokens_per_invocation"] == \
        eng._chunk * eng._num_slots


def test_device_seconds_attribution_sums_to_busy_total(params, mesh1):
    """Tick attribution conserves time: the per-program device-second
    counters sum to the engine's cumulative dispatched-work interval
    (each tick's busy interval is split, never invented)."""
    eng = InferenceEngine(CFG, mesh1, params,
                          EngineConfig(decode_chunk=2,
                                       max_new_tokens=8, num_slots=2))
    hs = [eng.submit(_prompt(6 + i, i)) for i in range(4)]
    eng.run_pending()
    assert all(h.done() for h in hs)
    programs = eng.profiler.program_report()
    attributed = sum(p["device_seconds"] for p in programs.values())
    assert attributed == pytest.approx(eng._busy_total_s, rel=1e-6)
    assert attributed > 0
    # every dispatched program gained invocations and flops totals
    assert programs["decode"]["invocations"] > 0
    assert programs["decode"]["flops_total"] > 0


# ---------------------------------------------------------------------------
# per-tenant metering: exact fleet totals
# ---------------------------------------------------------------------------

def test_fleet_tenant_costs_sum_exactly(params, mesh1):
    """The acceptance bar: across a 2-replica, 3-tenant run the
    federated per-tenant counters equal the sum of per-request bills
    (terminal trace events carry each request's accumulated cost),
    and the fleet total equals the sum over tenants."""
    router = Router(cfg=CFG, mesh=mesh1, params=params,
                    num_replicas=2,
                    engine_config=EngineConfig(
                        decode_chunk=2, max_new_tokens=4,
                        max_batch_size=2, backoff_base_s=0.0))
    tenants = ["acme", "beta", "gamma"]
    try:
        hs = [router.submit(_prompt(6 + i % 3, i),
                            tenant=tenants[i % 3])
              for i in range(9)]
        router.run_pending()
        assert all(h.done() for h in hs)
        rep = router.cost_report()
        # per-request bills, harvested from the replica engines'
        # terminal trace events
        bills: dict = {}
        for ctl in router._ctls:
            for ev in ctl.replica.engine.recorder.recent(10_000):
                if ev.kind == "finished":
                    t = ev.data.get("tenant", "default")
                    bills[t] = (bills.get(t, 0.0)
                                + ev.data.get("cost_flops", 0.0))
        assert set(rep["tenants"]) == set(tenants)
        for t in tenants:
            assert rep["tenants"][t]["flops"] == pytest.approx(
                bills[t], rel=1e-12), t
            assert rep["tenants"][t]["flops"] > 0
        assert rep["total_flops"] == pytest.approx(
            sum(v["flops"] for v in rep["tenants"].values()),
            rel=1e-12)
        assert rep["total_flops"] == pytest.approx(
            sum(bills.values()), rel=1e-12)
    finally:
        router.close()


def test_prefix_hit_bills_only_suffix_tokens(params, mesh1):
    """Zero-cost path #1: a prefix-cache hit. The second tenant's
    prompt shares the first's page-aligned prefix, so it bills ONLY
    the recomputed suffix tokens — the cached prefix is free in the
    bill exactly as it is free on the device."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        EngineConfig(decode_chunk=2, max_new_tokens=4, num_slots=1,
                     max_batch_size=1, paged=True, page_size=4))
    shared = np.arange(16, dtype=np.int32)
    p1 = np.concatenate([shared, np.asarray([5, 7], np.int32)])
    h1 = eng.submit(p1, tenant="first")
    eng.run_pending()
    assert h1.done()
    p2 = np.concatenate([shared, np.asarray([6, 9], np.int32)])
    h2 = eng.submit(p2, tenant="second")
    eng.run_pending()
    assert h2.done()
    rep = eng.profiler.meter.report()["tenants"]
    assert rep["first"]["prefill_tokens"] == p1.shape[0]
    # the hit covers the page-aligned shared prefix (16 tokens):
    # tenant two pays for the 2-token tail only
    assert rep["second"]["prefill_tokens"] == 2
    assert rep["second"]["flops"] < rep["first"]["flops"]
    # decode tokens bill identically (max_new=4: one token from the
    # prefill sample + 3 decode-chunk tokens)
    assert rep["second"]["decode_tokens"] == \
        rep["first"]["decode_tokens"] == 3


def test_migrated_chain_bills_only_private_tail(params, mesh1):
    """Zero-cost path #2: a migrated prefix chain. Engine B adopts
    engine A's exported cache chain at seating, so the request admits
    as a prefix hit and its tenant bills only the private tail — KV
    that arrived as bytes is never billed as FLOPs."""
    ec = EngineConfig(decode_chunk=2, max_new_tokens=4, num_slots=1,
                      max_batch_size=1, paged=True, page_size=4)
    shared = np.arange(16, dtype=np.int32)
    prompt = np.concatenate([shared, np.asarray([6, 9], np.int32)])
    a = InferenceEngine(CFG, mesh1, params, ec)
    ha = a.submit(np.concatenate(
        [shared, np.asarray([5, 7], np.int32)]), tenant="warm")
    a.run_pending()
    assert ha.done()
    dg = a.health()["prefix_digest"]
    assert dg["top"], "engine A must advertise its cached chain"
    chain_hash, chain_tokens = dg["top"][0]
    ho = a.export_cached_chain(int(chain_hash))
    assert ho is not None and ho.source == "cache"

    b = InferenceEngine(CFG, mesh1, params, ec)
    hb = b.submit(prompt, kv=ho, tenant="cold")
    b.run_pending()
    assert hb.done()
    rep = b.profiler.meter.report()["tenants"]
    assert rep["cold"]["prefill_tokens"] == \
        prompt.shape[0] - int(chain_tokens)
    # and the tokens are exact vs a no-migration run
    ref = InferenceEngine(CFG, mesh1, params, ec)
    href = ref.submit(prompt)
    ref.run_pending()
    np.testing.assert_array_equal(hb.result(0), href.result(0))


# ---------------------------------------------------------------------------
# cardinality: hostile tenant streams
# ---------------------------------------------------------------------------

def test_hostile_tenant_stream_stays_inside_the_budget(params, mesh1):
    """A stream of 40 distinct tenant ids against tenant_top_n=4:
    only the first 4 get their own label, the rest fold into "other"
    — the scrape has at most 5 tenant series per family and passes
    federation.check_cardinality."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        EngineConfig(decode_chunk=2, max_new_tokens=2, num_slots=4,
                     max_queue=128, tenant_top_n=4))
    hs = [eng.submit(_prompt(6, i), tenant=f"hostile-{i:03d}")
          for i in range(40)]
    eng.run_pending()
    assert all(h.done() for h in hs)
    fam = eng.registry.get("serving_request_cost_flops")
    labels = {v[0] for v, _ in fam.collect()}
    assert len(labels) <= 5
    assert "other" in labels
    rep = eng.profiler.meter.report()
    assert rep["distinct_tenants_seen"] == 40
    assert rep["bills_folded_to_other"] == 36
    # the "other" row carries everyone past the bound
    assert rep["tenants"]["other"]["prefill_tokens"] == 36 * 6
    check_cardinality(json_snapshot(eng.registry), budget=64)


def test_federated_hostile_tenants_pass_cardinality(params, mesh1):
    """The fleet-level version of the bound: hostile tenants through
    a 2-replica router, the FEDERATED snapshot (tenant labels merged
    across replicas) still passes check_cardinality."""
    router = Router(cfg=CFG, mesh=mesh1, params=params,
                    num_replicas=2,
                    engine_config=EngineConfig(
                        decode_chunk=2, max_new_tokens=2,
                        max_batch_size=2, tenant_top_n=4,
                        max_queue=128))
    try:
        hs = [router.submit(_prompt(6, i), tenant=f"h{i}")
              for i in range(24)]
        router.run_pending()
        assert all(h.done() for h in hs)
        snap = router.federate()
        check_cardinality(snap, budget=64)
        # per-family bound: <= (top_n + other) per replica
        n = len(snap["serving_request_cost_flops"]["samples"])
        assert n <= 2 * 5
        rep = router.cost_report()
        assert "other" in rep["tenants"]
    finally:
        router.close()


# ---------------------------------------------------------------------------
# cache-warm restarts: cost tables without compiles
# ---------------------------------------------------------------------------

def test_cache_warm_restart_reports_complete_cost_table(
        tmp_path, params, mesh1):
    """The acceptance bar: a compile-cache-warm restart — zero jit
    compiles, every program an AOT load — still has a complete
    per-program cost table (the analysis is persisted beside each
    cached executable and loaded with it)."""
    from tests.test_compile_cache import _fresh_process

    def build():
        return InferenceEngine(
            CFG, mesh1, params,
            EngineConfig(decode_chunk=2, max_new_tokens=6,
                         num_slots=2, compile_cache_dir=str(tmp_path),
                         warmup_on_init=True))

    _fresh_process()
    cold = build()
    cold_table = cold.profiler.program_report()
    assert cold.last_warmup["jit"] > 0

    _fresh_process()
    warm = build()
    assert warm.last_warmup["jit"] == 0, \
        "a warm restart must not XLA-compile anything"
    assert warm.last_warmup["aot_cache"] == \
        warm.last_warmup["programs"] > 0
    warm_table = warm.profiler.program_report()
    assert set(warm_table) == set(cold_table)
    for label in cold_table:
        assert warm_table[label]["flops_per_invocation"] == \
            cold_table[label]["flops_per_invocation"], label
        assert warm_table[label]["flops_per_invocation"] > 0, label
    # and traffic bills off the loaded table immediately
    h = warm.submit(_prompt(), tenant="t")
    warm.run_pending()
    assert h.done() and h.cost_flops > 0


def test_old_format_cache_entry_degrades_to_lazy_recompute(tmp_path):
    """A round-17-format entry (3-tuple frame, no cost sidecar) still
    loads its executable — load_entry returns meta=None and the
    caller recomputes the analysis from the LOADED executable. An old
    entry degrades; it never becomes a cache miss."""
    import pickle
    import zlib
    from deeplearning4j_tpu.serving import CompileCache
    from deeplearning4j_tpu.serving.compile_cache import _MAGIC

    cache = CompileCache(tmp_path)
    fn = jax.jit(lambda a, b: a @ b)
    x = np.zeros((4, 8), np.float32)
    y = np.zeros((8, 2), np.float32)
    exe = fn.lower(x, y).compile()
    from jax.experimental import serialize_executable as se
    # hand-write the PRE-META frame (exactly what rounds 17-19 stored)
    payload = pickle.dumps(se.serialize(exe))
    blob = (_MAGIC + zlib.crc32(payload).to_bytes(4, "little")
            + payload)
    key = "decode-oldformat"
    cache.path(key).write_bytes(blob)

    loaded, meta = cache.load_entry(key)
    assert loaded is not None and meta is None
    assert cache.stats()["corrupt"] == 0
    # lazy recompute from the loaded executable: full analysis
    cost = cost_from_compiled(loaded)
    assert cost["flops"] == 2 * 4 * 8 * 2
    np.testing.assert_array_equal(np.asarray(loaded(x, y)), x @ y)


def test_meta_roundtrip_beside_executable(tmp_path):
    """The new frame: store(meta=) publishes the cost dict beside the
    executable, load_entry returns both, and the version field rides
    in-payload (a future meta schema drops the sidecar, never the
    executable)."""
    from deeplearning4j_tpu.serving import CompileCache

    cache = CompileCache(tmp_path)
    fn = jax.jit(lambda a: a * 2.0)
    x = np.zeros((4,), np.float32)
    exe = fn.lower(x).compile()
    cost = cost_from_compiled(exe)
    assert cache.store("p-meta", exe, meta={"cost": cost})
    loaded, meta = cache.load_entry("p-meta")
    assert loaded is not None
    assert meta["cost"] == cost
    assert meta["meta_version"] >= 1


# ---------------------------------------------------------------------------
# rooflines, MFU, units
# ---------------------------------------------------------------------------

def test_roofline_classification():
    """Arithmetic intensity vs ridge point: left = memory-bound,
    right = compute-bound, unknown peaks = unknown."""
    # ridge = 1e12 / 1e9 = 1000 FLOPs/byte
    r = roofline(flops=1e6, bytes_=1e5, peak_flops=1e12,
                 peak_bytes_per_s=1e9)
    assert r["bound"] == "memory" and \
        r["intensity_flops_per_byte"] == 10.0
    r = roofline(flops=1e9, bytes_=1e5, peak_flops=1e12,
                 peak_bytes_per_s=1e9)
    assert r["bound"] == "compute"
    assert roofline(1e6, 1e5, None, None)["bound"] == "unknown"
    assert roofline(1e6, 0.0, 1e12, 1e9)["bound"] == "unknown"


def test_mfu_and_roofline_with_injected_peaks(params, mesh1):
    """With injected chip peaks (the CPU container has none) the live
    MFU gauge reads positive after traffic and every program gets a
    definite roofline verdict; the chosen ridge makes the small
    decode geometry memory-bound and the whole report coherent."""
    registry = MetricsRegistry()
    profiler = EngineProfiler(registry, peak_flops=1e15,
                              peak_bytes_per_s=1e9)
    eng = InferenceEngine(CFG, mesh1, params,
                          EngineConfig(decode_chunk=2,
                                       max_new_tokens=6, num_slots=2),
                          registry=registry, profiler=profiler)
    h = eng.submit(_prompt(), tenant="t")
    eng.run_pending()
    assert h.done()
    assert profiler.mfu() > 0
    rep = eng.profile_report()
    assert rep["ridge_flops_per_byte"] == 1e15 / 1e9
    for label, row in rep["programs"].items():
        # tiny-model serving programs sit far left of a 1e6 ridge
        assert row["bound"] == "memory", label
    gauge = registry.get("serving_mfu")
    assert gauge.value > 0
    # debugz carries the same report
    assert "profiling" in eng.debugz()


def test_chip_peak_tables():
    """The serving roofline's denominators: known TPU kinds resolve
    both peaks; unknown device kinds (this CPU) resolve None."""
    class _Dev:
        device_kind = "TPU v5 lite"

    assert util_flops.chip_peak_flops(_Dev()) == 197e12
    assert util_flops.chip_peak_bytes_per_s(_Dev()) == 819e9
    class _Cpu:
        device_kind = "cpu"

    assert util_flops.chip_peak_bytes_per_s(_Cpu()) is None


def test_null_profiler_disables_by_injection(params, mesh1):
    """profiler=NULL_PROFILER: no serving_mfu / serving_program_* /
    tenant series in the scrape, zero per-request bills — the
    profiling_overhead benchmark's off arm."""
    eng = InferenceEngine(CFG, mesh1, params,
                          EngineConfig(decode_chunk=2,
                                       max_new_tokens=4),
                          profiler=NULL_PROFILER)
    h = eng.submit(_prompt(), tenant="t")
    eng.run_pending()
    assert h.done()
    text = prometheus_text(eng.registry)
    assert "serving_mfu" not in text
    assert "serving_program_flops" not in text
    assert "serving_program_device_seconds" not in text
    assert "serving_request_cost" not in text
    assert "serving_tenant_tokens" not in text
    assert h.cost_flops == 0.0
    assert "profiling" not in eng.debugz()


def test_tenant_meter_unit():
    """TenantMeter in isolation: top-N assignment, fold accounting,
    ranking by FLOPs."""
    m = TenantMeter(MetricsRegistry(), top_n=2)
    m.bill("a", 100.0, 10.0, 5, "prefill")
    m.bill("b", 300.0, 30.0, 5, "decode")
    m.bill("c", 50.0, 5.0, 1, "decode")       # folds: top_n reached
    m.bill("d", 60.0, 6.0, 1, "decode")       # folds
    m.bill(None, 10.0, 1.0, 1, "decode")      # "default" folds too
    rep = m.report()
    assert list(rep["tenants"]) == ["b", "other", "a"]
    assert rep["tenants"]["other"]["flops"] == 120.0
    assert rep["bills_folded_to_other"] == 3


# ---------------------------------------------------------------------------
# on-demand capture: /profilez
# ---------------------------------------------------------------------------

def test_profilez_unsupported_and_unwired(params, mesh1):
    """No profile_dir configured -> the engine answers 503; an
    exporter without the callable wired -> 404."""
    eng = InferenceEngine(CFG, mesh1, params,
                          EngineConfig(max_new_tokens=2))
    code, body = eng.profilez(1.0)
    assert code == 503 and "unsupported" in body["error"]
    srv = MetricsServer(eng.registry, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/profilez?seconds=1",
                                   timeout=5)
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_profilez_capture_single_flight(tmp_path, params, mesh1):
    """The wired endpoint: a capture starts (200), a second request
    while it runs is rejected 503 BUSY (single-flight), bad seconds
    are 400, and the bounded trace lands in the configured
    directory."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        EngineConfig(max_new_tokens=2,
                     profile_dir=str(tmp_path / "prof")))
    srv = MetricsServer(eng.registry, port=0, profilez=eng.profilez)
    try:
        with urllib.request.urlopen(
                srv.url + "/profilez?seconds=0.3", timeout=10) as r:
            assert r.getcode() == 200
            body = json.loads(r.read().decode())
            assert body["started"] and body["seconds"] == 0.3
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/profilez?seconds=0.3",
                                   timeout=10)
        assert ei.value.code == 503
        assert "in progress" in json.loads(
            ei.value.read().decode())["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/profilez?seconds=nope",
                                   timeout=10)
        assert ei.value.code == 400
        # run a little traffic DURING the capture so it has content
        h = eng.submit(_prompt())
        eng.run_pending()
        assert h.done()
        deadline = time.time() + 10
        while eng._capture.active and time.time() < deadline:
            time.sleep(0.05)
        assert not eng._capture.active, "capture must stop itself"
        assert any((tmp_path / "prof").rglob("*")), \
            "the capture must write into the configured directory"
        # and the engine accepts a NEW capture after the first ends
        code, _ = eng.profilez(0.05)
        assert code == 200
        deadline = time.time() + 10
        while eng._capture.active and time.time() < deadline:
            time.sleep(0.05)
    finally:
        srv.stop()


def test_profile_capture_unit():
    """ProfileCapture argument semantics without touching the real
    profiler: no directory -> 503, bad seconds -> 400, max_seconds
    clamps."""
    cap = ProfileCapture(None)
    assert cap.capture(1.0)[0] == 503
    cap = ProfileCapture("/tmp/never-used", max_seconds=2.0)
    assert cap.capture("x")[0] == 400
    assert cap.capture(-1)[0] == 400


def test_fleet_profilez_fans_to_replicas(params, mesh1, tmp_path):
    """Router.profilez fans the capture per replica: with no replica
    configured for capture the fleet answer is 503 with per-replica
    errors; cost/profile reports still work."""
    router = Router(cfg=CFG, mesh=mesh1, params=params,
                    num_replicas=2,
                    engine_config=EngineConfig(
                        decode_chunk=2, max_new_tokens=2,
                        max_batch_size=2))
    try:
        code, body = router.profilez(0.5)
        assert code == 503 and body["started"] == 0
        assert len(body["replicas"]) == 2
        pr = router.profile_report()
        assert set(pr) == {"serving/0", "serving/1"}
    finally:
        router.close()
