"""UI component model tests (reference: deeplearning4j-ui-components —
JSON round-trip per component type + standalone page rendering, the
TestRendering/TestComponentSerialization analog)."""
import json

import pytest

from deeplearning4j_tpu.ui import (ChartHistogram, ChartHorizontalBar,
                                   ChartLine, ChartScatter,
                                   ChartStackedArea, ChartTimeline,
                                   Component, ComponentDiv, ComponentTable,
                                   ComponentText, DecoratorAccordion,
                                   StaticPageUtil, StyleChart, StyleText)


def _sample_components():
    line = (ChartLine("score", StyleChart())
            .add_series("train", [0, 1, 2, 3], [1.0, 0.6, 0.4, 0.3])
            .add_series("val", [0, 1, 2, 3], [1.1, 0.8, 0.6, 0.55]))
    scatter = ChartScatter("embedding").add_series(
        "pts", [0.1, 0.5, 0.9], [0.2, 0.7, 0.3])
    hist = (ChartHistogram("weights")
            .add_bin(-1.0, -0.5, 3).add_bin(-0.5, 0.0, 10)
            .add_bin(0.0, 0.5, 12).add_bin(0.5, 1.0, 2))
    bars = (ChartHorizontalBar("per-class F1")
            .add_value("cat", 0.91).add_value("dog", 0.84))
    stacked = (ChartStackedArea("time breakdown")
               .set_x_values([0, 1, 2])
               .add_series("fwd", [1, 1, 1]).add_series("bwd", [2, 2, 1]))
    timeline = ChartTimeline("phases").add_lane(
        "worker0", [{"startTimeMs": 0, "endTimeMs": 40,
                     "entryLabel": "fit", "color": "#3b8746"},
                    {"startTimeMs": 40, "endTimeMs": 55}])
    table = ComponentTable(header=["metric", "value"],
                           content=[["accuracy", 0.97], ["f1", 0.95]])
    text = ComponentText("Training report", StyleText(font_size=16))
    acc = DecoratorAccordion("details", False, table, hist)
    div = ComponentDiv(None, text, line)
    return [line, scatter, hist, bars, stacked, timeline, table, text,
            acc, div]


@pytest.mark.parametrize("comp", _sample_components(),
                         ids=lambda c: type(c).__name__)
def test_json_round_trip(comp):
    s = comp.to_json()
    d = json.loads(s)
    assert d["componentType"] == type(comp).__name__
    back = Component.from_json(s)
    assert type(back) is type(comp)
    # data fields survive the round trip (style is presentation-only)
    d2 = back.to_dict()
    for k, v in comp._fields().items():
        assert d2[k] == d[k], k


def test_render_static_page(tmp_path):
    comps = _sample_components()
    html = StaticPageUtil.render_html(comps, title="report")
    assert html.startswith("<!DOCTYPE html>")
    assert html.count("<svg") >= 6
    assert "Training report" in html
    assert "<table" in html and "accuracy" in html
    assert "<details open>" in html
    path = tmp_path / "report.html"
    StaticPageUtil.save_html(comps, str(path), title="report")
    assert path.read_text() == html


def test_chart_line_length_mismatch_raises():
    with pytest.raises(ValueError):
        ChartLine("x").add_series("bad", [1, 2], [1.0])


def test_unknown_component_type_raises():
    with pytest.raises(ValueError):
        Component.from_json(json.dumps({"componentType": "Nope"}))
