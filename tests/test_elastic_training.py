"""Elastic sharded training suite (ISSUE-18).

Unit half: the ZeRO-1 partitioner / flat-vector codec / deterministic
data cursor the elastic coordinator builds on, plus the elastic-off
guarantee (importing the subsystem changes NOTHING for non-elastic
training — bit-identical params, no new metric families).

`multiproc` half: REAL worker processes (train/elastic_worker.py)
under the membership scenarios the acceptance criteria name, each
asserted BIT-EXACT against `reference_run` — the membership-free
single-process oracle:

- SIGKILL one of three workers mid-run, re-add one → final losses and
  params bit-equal the uninterrupted run, and each worker's measured
  updater footprint is the analytic 1/N shard;
- shrink 3→2 then grow 2→3 → same invariant (resharding is a pure
  function of membership SIZE, never of which worker died);
- a straggler drops to SparkNet-style loose sync (typed `elastic`
  events) and resyncs to strict once caught up — zero lost steps;
- a hung worker exhausts `stale_bound`, is evicted, and the lossy
  resize replays from the published checkpoint — exactness RESTORED,
  bit-equal to the oracle with the surviving membership.

Every blocking wait is hard-bounded and the shared
`helpers.child_killing_watchdog` kills worker processes if a test
wedges, so this suite can never hang tier-1.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import TransformerConfig
from deeplearning4j_tpu.observability.events import FlightRecorder
from deeplearning4j_tpu.observability.export import prometheus_text
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel.failure import ElasticFaultInjector
from deeplearning4j_tpu.parallel.fsdp import (flatten_tree, unflatten_tree,
                                              zero1_partition)
from deeplearning4j_tpu.train.elastic import (ElasticConfig,
                                              ElasticCoordinator,
                                              data_batch, init_flat_params,
                                              param_template, reference_run)
from helpers import child_killing_watchdog

#: tiny model: the properties under test are membership/determinism,
#: not capacity — worker startup (spawn + jit warmup) dominates anyway
CFG = TransformerConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                        max_len=16)

#: hard wall for anything that could block on a child process
HARD_TIMEOUT_S = 240.0


def _ecfg(tmp_path, **kw):
    base = dict(checkpoint_dir=str(tmp_path / "ckpt"), num_workers=3,
                microbatches_per_step=6, microbatch_size=2, seq_len=8,
                checkpoint_every=1)
    base.update(kw)
    return ElasticConfig(**base)


# ---------------------------------------------------------------------------
# unit: partitioner / codec / data cursor
# ---------------------------------------------------------------------------

def test_zero1_partition_covers_contiguously():
    for n, k in ((10, 3), (4528, 3), (7, 7), (5, 8), (0, 2), (100, 1)):
        bounds = zero1_partition(n, k)
        assert len(bounds) == k
        assert bounds[0][0] == 0 and bounds[-1][1] == n
        for (lo, hi), (lo2, _) in zip(bounds, bounds[1:]):
            assert hi == lo2 and lo <= hi
        # remainder spreads over the FIRST shards; sizes differ by <= 1
        sizes = [hi - lo for lo, hi in bounds]
        assert max(sizes) - min(sizes) <= 1
        assert sorted(sizes, reverse=True) == sizes
    # deterministic: same inputs, same cuts (the resharding contract)
    assert zero1_partition(4528, 3) == zero1_partition(4528, 3)
    with pytest.raises(ValueError):
        zero1_partition(-1, 2)
    with pytest.raises(ValueError):
        zero1_partition(10, 0)


def test_flatten_unflatten_roundtrip_bit_exact():
    template = param_template(CFG)
    flat = init_flat_params(CFG, params_seed=3)
    tree = unflatten_tree(flat, template)
    back = flatten_tree(tree)
    assert back.dtype == np.float32
    assert np.array_equal(back, flat)
    with pytest.raises(ValueError):
        unflatten_tree(flat[:-1], template)


def test_data_batch_is_a_pure_function_of_the_cursor():
    a_tok, a_tgt = data_batch(32, 8, 4, step=5, microbatch=2, seed=0)
    b_tok, b_tgt = data_batch(32, 8, 4, step=5, microbatch=2, seed=0)
    assert np.array_equal(a_tok, b_tok) and np.array_equal(a_tgt, b_tgt)
    assert a_tok.shape == (4, 8) and a_tgt.shape == (4, 8)
    assert a_tok.min() >= 0 and a_tok.max() < 32
    # targets are the next-token shift of the same underlying sequence
    c_tok, _ = data_batch(32, 8, 4, step=6, microbatch=2, seed=0)
    d_tok, _ = data_batch(32, 8, 4, step=5, microbatch=3, seed=0)
    assert not np.array_equal(a_tok, c_tok)
    assert not np.array_equal(a_tok, d_tok)


def test_elastic_off_training_is_unchanged(tmp_path):
    """Elastic-off guarantee: with the subsystem imported and its
    config built, a FaultTolerantTrainer run is bit-identical to one
    without any of that, and its scrape carries no training_elastic_*
    series (registration is lazy in the coordinator constructor)."""
    from deeplearning4j_tpu.datasets.iterators import BaseDatasetIterator
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.failure import FaultTolerantTrainer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]

    def _run(subdir, registry):
        conf = NeuralNetConfiguration(seed=7, updater="adam",
                                      learning_rate=0.01).list(
            DenseLayer(n_in=6, n_out=8, activation="tanh"),
            OutputLayer(n_out=2, activation="softmax",
                        loss_function="mcxent"))
        net = MultiLayerNetwork(conf).init()
        t = FaultTolerantTrainer(net, str(tmp_path / subdir),
                                 checkpoint_frequency=2,
                                 use_orbax=False, registry=registry)
        assert t.fit(BaseDatasetIterator(x, y, 16), epochs=1) is True
        return np.asarray(net.params_flat())

    a = _run("a", MetricsRegistry())
    # build the elastic config between the runs: merely touching the
    # subsystem must not perturb non-elastic training
    _ecfg(tmp_path)
    reg = MetricsRegistry()
    b = _run("b", reg)
    assert np.array_equal(a, b)
    assert "training_elastic" not in prometheus_text(reg)


def test_bench_mfu_regression_gate():
    """ISSUE-18 satellite: `bench.py --check`'s gate logic — a gated
    flagship arm whose achieved FLOP/s drops more than the tolerance
    below the BASELINE.json floor fails; within-tolerance dips,
    null-floor entries, and ungated configs pass. Pure-function test:
    no bench runs."""
    import importlib.util
    import json
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    spec = importlib.util.spec_from_file_location("_bench_gate",
                                                  root / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    baseline = {"flops_gate": {"elastic_train": 1e9,
                               "transformer_lm_12L512d_T2048": 1e13,
                               "recorded_not_gated": None}}
    ok = [{"config": "elastic_train", "flops_per_sec": 8.5e8},
          {"config": "transformer_lm_12L512d_T2048",
           "flops_per_sec": 1.1e13},
          {"config": "some_other_bench", "value": 1}]
    assert bench.check_gate(ok, baseline, tolerance=0.2) == []

    # >20% drop on one arm: exactly that arm fails
    bad = [{"config": "elastic_train", "flops_per_sec": 7.9e8},
           {"config": "transformer_lm_12L512d_T2048",
            "flops_per_sec": 1e13}]
    fails = bench.check_gate(bad, baseline, tolerance=0.2)
    assert len(fails) == 1 and fails[0].startswith("elastic_train")

    # a tighter tolerance flips the same lines to failing
    assert len(bench.check_gate(ok, baseline, tolerance=0.1)) == 1

    # missing line, errored line, and a line with no flops_per_sec
    # are all failures — silence must not pass the gate
    assert len(bench.check_gate([], baseline)) == 2
    errs = bench.check_gate(
        [{"config": "elastic_train", "error": "Boom: x"},
         {"config": "transformer_lm_12L512d_T2048", "value": 5}],
        baseline)
    assert len(errs) == 2

    # metric-keyed dict entries (ISSUE-19): the spec-throughput gate
    # reads its own bench-line key, with the config and floor named
    # in the failure
    mbase = {"flops_gate": {"spec_pipeline_x": {
        "metric": "tokens_per_sec_pipelined_spec", "value": 1000.0}}}
    assert bench.check_gate(
        [{"config": "spec_pipeline_x",
          "tokens_per_sec_pipelined_spec": 850.0}],
        mbase, tolerance=0.2) == []
    mfails = bench.check_gate(
        [{"config": "spec_pipeline_x",
          "tokens_per_sec_pipelined_spec": 750.0}],
        mbase, tolerance=0.2)
    assert len(mfails) == 1
    assert mfails[0].startswith("spec_pipeline_x")
    assert "tokens_per_sec_pipelined_spec" in mfails[0]
    assert "8.000e+02" in mfails[0]          # the floor, by value
    # a dict line missing the keyed metric fails loudly too
    assert len(bench.check_gate(
        [{"config": "spec_pipeline_x", "flops_per_sec": 1e12}],
        mbase)) == 1

    # the shipped BASELINE.json actually carries the gate, and the
    # elastic bench reports through it
    shipped = json.loads((root / "BASELINE.json").read_text())
    assert "elastic_train" in shipped["flops_gate"]
    assert "transformer_lm_12L512d_T2048" in shipped["flops_gate"]
    assert "spec_pipeline_4L192d_Ns8_K7" in shipped["flops_gate"]
    for v in shipped["flops_gate"].values():
        floor = v.get("value") if isinstance(v, dict) else v
        assert (floor or 0) > 0


# ---------------------------------------------------------------------------
# multiproc: real worker processes under membership change
# ---------------------------------------------------------------------------

def _coordinator(tmp_path, register, injector=None, registry=None,
                 recorder=None, **kw):
    ecfg = _ecfg(tmp_path, **kw)
    co = ElasticCoordinator(CFG, ecfg,
                            fault_injector=injector, registry=registry,
                            recorder=recorder)
    register(co)
    return co, ecfg


@pytest.mark.multiproc
def test_kill_and_rejoin_bit_reproducible(tmp_path):
    """SIGKILL one of three workers at step 3, admit a replacement at
    step 5: every loss and the final params bit-equal the
    uninterrupted oracle, and the measured per-worker updater bytes
    are the analytic 1/N contiguous shard."""
    rec = FlightRecorder(capacity=256)
    with child_killing_watchdog(HARD_TIMEOUT_S) as register:
        co, ecfg = _coordinator(
            tmp_path, register, recorder=rec, checkpoint_every=2,
            injector=ElasticFaultInjector(kill_at={3: 1}, join_at={5: 3}))
        out = co.run(8)
    ref = reference_run(CFG, ecfg, 8)
    assert out["losses"] == ref["losses"]
    assert np.array_equal(out["params"], ref["params"])
    assert out["workers"] == 3 and out["resizes"] == 2
    assert out["replayed_steps"] > 0
    acts = [e.data.get("action") for e in rec.recent(kind="elastic")]
    assert "kill_detected" in acts and "replay" in acts
    assert acts.count("resize") == 2
    # 1/N updater footprint: measured == analytic for every live worker
    n = out["n_params"]
    analytic = sorted(3 * 4 * (hi - lo)
                      for lo, hi in zero1_partition(n, 3))
    assert sorted(out["worker_state_bytes"].values()) == analytic
    assert sum(out["worker_state_bytes"].values()) == 3 * 4 * n


@pytest.mark.multiproc
def test_shrink_then_grow_bit_reproducible(tmp_path):
    """Shrink 3→2 (crash, no replacement) then grow 2→3: resharding
    is a pure function of membership size, so the whole trajectory
    stays bit-equal to the oracle."""
    with child_killing_watchdog(HARD_TIMEOUT_S) as register:
        co, ecfg = _coordinator(
            tmp_path, register,
            injector=ElasticFaultInjector(kill_at={2: 0}, join_at={5: 9}))
        out = co.run(8)
    ref = reference_run(CFG, ecfg, 8)
    assert out["losses"] == ref["losses"]
    assert np.array_equal(out["params"], ref["params"])
    assert out["workers"] == 3 and out["resizes"] == 2
    n = out["n_params"]
    assert sorted(out["worker_state_bytes"].values()) == sorted(
        3 * 4 * (hi - lo) for lo, hi in zero1_partition(n, 3))


@pytest.mark.multiproc
def test_loose_sync_engages_and_recovers(tmp_path):
    """A slowed worker misses `sync_every` barriers, drops to loose
    sync (typed events, stale counter), keeps training with zero lost
    steps, and resyncs to strict once un-slowed."""
    rec = FlightRecorder(capacity=256)
    reg = MetricsRegistry()
    with child_killing_watchdog(HARD_TIMEOUT_S) as register:
        co, _ = _coordinator(
            tmp_path, register, recorder=rec, registry=reg,
            injector=ElasticFaultInjector(
                slow_at={2: (1, 0.5), 6: (1, 0.0)}),
            step_timeout_s=0.15, sync_every=1, stale_bound=30)
        out = co.run(10)
    acts = [e.data.get("action") for e in rec.recent(kind="elastic")]
    assert "loose_enter" in acts and "resync" in acts
    assert "evict" not in acts
    assert len(out["losses"]) == 10          # zero lost steps
    assert np.isfinite(out["final_loss"])
    assert out["workers"] == 3
    assert reg.get("training_elastic_stale_steps_total").value > 0
    assert reg.get("training_elastic_workers").value == 3


@pytest.mark.multiproc
def test_hang_evicts_and_restores_bit_exactness(tmp_path):
    """A SIGSTOPped worker exhausts `stale_bound`, is evicted (ONE
    typed evict), and the lossy resize replays from the published
    checkpoint — discarding its loose steps restores bit-exactness
    against the 2-worker oracle tail."""
    rec = FlightRecorder(capacity=256)
    with child_killing_watchdog(HARD_TIMEOUT_S) as register:
        co, ecfg = _coordinator(
            tmp_path, register, recorder=rec,
            injector=ElasticFaultInjector(hang_at={3: 2}),
            step_timeout_s=0.15, sync_every=1, stale_bound=2)
        out = co.run(8)
    ref = reference_run(CFG, ecfg, 8)
    assert out["losses"] == ref["losses"]
    assert np.array_equal(out["params"], ref["params"])
    assert out["workers"] == 2 and out["replayed_steps"] > 0
    acts = [e.data.get("action") for e in rec.recent(kind="elastic")]
    assert acts.count("evict") == 1
    assert "replay" in acts
