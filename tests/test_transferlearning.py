"""Transfer learning tests (reference test analog:
deeplearning4j-core/src/test/java/org/deeplearning4j/nn/transferlearning/
TransferLearningMLNTest.java, TransferLearningHelperTest.java)."""
import numpy as np

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
from deeplearning4j_tpu.nn.transferlearning import (FineTuneConfiguration,
                                                    TransferLearning,
                                                    TransferLearningHelper)


def _net():
    conf = (NeuralNetConfiguration(seed=5, updater="sgd", learning_rate=0.1)
            .list(DenseLayer(n_in=4, n_out=10, activation="tanh"),
                  DenseLayer(n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax",
                              loss_function="mcxent")))
    return MultiLayerNetwork(conf).init()


def _data(rng, n=16):
    x = rng.rand(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return x, y


def test_feature_extractor_freezes_params(rng):
    src = _net()
    x, y = _data(rng)
    tl = (TransferLearning.Builder(src)
          .set_feature_extractor(1)
          .build())
    assert isinstance(tl.layers[0], FrozenLayer)
    assert isinstance(tl.layers[1], FrozenLayer)
    w0_before = np.asarray(tl.params["layer_0"]["W"]).copy()
    w2_before = np.asarray(tl.params["layer_2"]["W"]).copy()
    tl.fit(x, y)
    np.testing.assert_array_equal(np.asarray(tl.params["layer_0"]["W"]),
                                  w0_before)
    assert np.abs(np.asarray(tl.params["layer_2"]["W"])
                  - w2_before).max() > 0


def test_frozen_params_copied_from_source(rng):
    src = _net()
    tl = TransferLearning.Builder(src).set_feature_extractor(0).build()
    np.testing.assert_array_equal(np.asarray(tl.params["layer_0"]["W"]),
                                  np.asarray(src.params["layer_0"]["W"]))


def test_nout_replace_reinitializes_both_sides(rng):
    src = _net()
    tl = (TransferLearning.Builder(src)
          .n_out_replace(1, 20, weight_init="xavier")
          .build())
    assert np.asarray(tl.params["layer_1"]["W"]).shape == (10, 20)
    assert np.asarray(tl.params["layer_2"]["W"]).shape == (20, 3)
    # layer 0 retained from source
    np.testing.assert_array_equal(np.asarray(tl.params["layer_0"]["W"]),
                                  np.asarray(src.params["layer_0"]["W"]))
    x, _ = _data(rng)
    assert np.asarray(tl.output(x)).shape == (16, 3)


def test_remove_and_add_output_layer(rng):
    src = _net()
    tl = (TransferLearning.Builder(src)
          .remove_output_layer()
          .add_layer(OutputLayer(n_out=7, activation="softmax",
                                 loss_function="mcxent"))
          .build())
    x, _ = _data(rng)
    assert np.asarray(tl.output(x)).shape == (16, 7)


def test_fine_tune_configuration_overrides(rng):
    src = _net()
    tl = (TransferLearning.Builder(src)
          .fine_tune_configuration(FineTuneConfiguration(
              learning_rate=0.01, updater="adam"))
          .build())
    assert tl.conf.training.updater == "adam"
    assert tl.conf.training.learning_rate == 0.01


def test_helper_featurize_matches_full_forward(rng):
    src = _net()
    helper = TransferLearningHelper(src, frozen_until=1)
    x, y = _data(rng)
    feats = helper.featurize(x)
    assert np.asarray(feats).shape == (16, 8)
    out_full = np.asarray(helper.net.output(x))
    out_tail = np.asarray(helper.output_from_featurized(feats))
    np.testing.assert_allclose(out_full, out_tail, rtol=1e-5, atol=1e-6)


def test_helper_fit_featurized_updates_composite(rng):
    src = _net()
    helper = TransferLearningHelper(src, frozen_until=1)
    x, y = _data(rng)
    feats = helper.featurize(x)
    w_before = np.asarray(helper.net.params["layer_2"]["W"]).copy()
    helper.fit_featurized(feats, y)
    assert np.abs(np.asarray(helper.net.params["layer_2"]["W"])
                  - w_before).max() > 0


def test_graph_transfer_learning_builder():
    """TransferLearning.GraphBuilder (reference:
    TransferLearning.GraphBuilder — freeze upstream of a vertex, replace
    an output head, fine-tune overrides)."""
    import jax
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    from deeplearning4j_tpu.nn.layers.misc import FrozenLayer

    conf = (NeuralNetConfiguration(seed=5, updater="adam",
                                   learning_rate=0.01, activation="tanh")
            .graph_builder().add_inputs("in")
            .add_layer("f1", DenseLayer(n_in=4, n_out=10), "in")
            .add_layer("f2", DenseLayer(n_in=10, n_out=8), "f1")
            .add_layer("out", OutputLayer(n_in=8, n_out=3,
                                          activation="softmax",
                                          loss_function="mcxent"), "f2")
            .set_outputs("out").build())
    src = ComputationGraph(conf).init()
    w_f1 = np.asarray(src.params["f1"]["W"]).copy()

    new = (TransferLearning.GraphBuilder(src)
           .fine_tune_configuration(FineTuneConfiguration(
               learning_rate=0.005))
           .set_feature_extractor("f2")
           .remove_vertex_and_connections("out")
           .add_layer("out2", OutputLayer(n_in=8, n_out=5,
                                          activation="softmax",
                                          loss_function="mcxent"), "f2")
           .set_outputs("out2")
           .build())
    # frozen closure: f1, f2 wrapped; params carried over
    assert isinstance(new.conf.vertices["f1"].vertex, FrozenLayer)
    assert isinstance(new.conf.vertices["f2"].vertex, FrozenLayer)
    np.testing.assert_array_equal(np.asarray(new.params["f1"]["W"]), w_f1)
    assert new.conf.network_outputs == ["out2"]
    assert new.conf.training.learning_rate == 0.005

    # training updates only the new head
    rng = np.random.default_rng(0)
    x = rng.random((32, 4), dtype=np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 32)]
    before_head = np.asarray(new.params["out2"]["W"]).copy()
    for _ in range(5):
        new.fit(x, y)
    np.testing.assert_array_equal(np.asarray(new.params["f1"]["W"]), w_f1)
    assert np.abs(np.asarray(new.params["out2"]["W"])
                  - before_head).max() > 0
    outs = new.output(x)
    assert np.asarray(outs[0]).shape == (32, 5)
