"""Fused Pallas LSTM vs the lax.scan reference path — the
CuDNNGradientChecks analog (reference: deeplearning4j-cuda/.../
CuDNNGradientChecks.java validates the cuDNN fast path against the
Java baseline numerically). Runs the kernel in interpret mode on the
CPU mesh."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.recurrent import LSTM, GravesLSTM
from deeplearning4j_tpu.ops.lstm import fused_lstm_available, fused_lstm_scan

B, T, F, H = 8, 12, 6, 128


def _mk(peephole: bool, seed=0):
    layer = (GravesLSTM if peephole else LSTM)(n_in=F, n_out=H,
                                               activation="tanh")
    params = layer.init_params(jax.random.PRNGKey(seed))
    # non-trivial values everywhere (zero peepholes would hide bugs)
    if peephole:
        k = jax.random.PRNGKey(seed + 1)
        for i, p in enumerate(("pI", "pF", "pO")):
            params[p] = 0.3 * jax.random.normal(
                jax.random.fold_in(k, i), (H,), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(seed + 2), (B, T, F),
                          jnp.float32)
    return layer, params, x


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv("DL4JTPU_FUSED_LSTM", "interpret")


@pytest.mark.parametrize("peephole", [False, True],
                         ids=["plain", "graves"])
@pytest.mark.parametrize("reverse", [False, True])
def test_fused_forward_matches_scan(peephole, reverse, monkeypatch):
    layer, params, x = _mk(peephole)
    carry = layer.initial_carry(B, jnp.float32)
    ys_fast, (h_f, c_f) = fused_lstm_scan(params, x, carry,
                                          reverse=reverse)
    monkeypatch.setenv("DL4JTPU_FUSED_LSTM", "0")
    ys_ref, (h_r, c_r) = layer.scan_sequence(params, x, carry=carry,
                                             reverse=reverse)
    np.testing.assert_allclose(np.asarray(ys_fast), np.asarray(ys_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(h_f), np.asarray(h_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(c_f), np.asarray(c_r),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("peephole", [False, True],
                         ids=["plain", "graves"])
def test_fused_backward_matches_scan(peephole, monkeypatch):
    layer, params, x = _mk(peephole)
    carry = layer.initial_carry(B, jnp.float32)
    tgt = jax.random.normal(jax.random.PRNGKey(9), (B, T, H), jnp.float32)

    def loss_fused(p, xx):
        ys, (h, c) = fused_lstm_scan(p, xx, carry)
        return jnp.sum((ys - tgt) ** 2) + jnp.sum(h * 0.1) + jnp.sum(
            c * 0.05)

    gp_fast, gx_fast = jax.grad(loss_fused, argnums=(0, 1))(params, x)

    monkeypatch.setenv("DL4JTPU_FUSED_LSTM", "0")

    def loss_ref(p, xx):
        ys, (h, c) = layer.scan_sequence(p, xx, carry=carry)
        return jnp.sum((ys - tgt) ** 2) + jnp.sum(h * 0.1) + jnp.sum(
            c * 0.05)

    gp_ref, gx_ref = jax.grad(loss_ref, argnums=(0, 1))(params, x)
    np.testing.assert_allclose(np.asarray(gx_fast), np.asarray(gx_ref),
                               rtol=1e-3, atol=1e-3)
    for k in gp_ref:
        np.testing.assert_allclose(
            np.asarray(gp_fast[k]), np.asarray(gp_ref[k]),
            rtol=1e-3, atol=1e-3, err_msg=k)


def test_dispatch_eligibility():
    x = jnp.zeros((B, T, F), jnp.float32)
    assert fused_lstm_available(x, 128, None, "sigmoid", "tanh")
    assert not fused_lstm_available(x, 100, None, "sigmoid", "tanh")
    assert not fused_lstm_available(x, 128, jnp.ones((B, T)), "sigmoid",
                                    "tanh")
    assert not fused_lstm_available(x, 128, None, "hardsigmoid", "tanh")
    assert not fused_lstm_available(
        jnp.zeros((5, T, F), jnp.float32), 128, None, "sigmoid", "tanh")
    os.environ["DL4JTPU_FUSED_LSTM"] = "0"
    try:
        assert not fused_lstm_available(x, 128, None, "sigmoid", "tanh")
    finally:
        os.environ["DL4JTPU_FUSED_LSTM"] = "interpret"


def test_layer_scan_sequence_dispatches_to_kernel():
    """End to end through the layer API: interpret-mode kernel output ==
    forced-fallback output."""
    layer, params, x = _mk(True, seed=4)
    ys_fast, _ = layer.scan_sequence(params, x)
    os.environ["DL4JTPU_FUSED_LSTM"] = "0"
    try:
        ys_ref, _ = layer.scan_sequence(params, x)
    finally:
        os.environ["DL4JTPU_FUSED_LSTM"] = "interpret"
    np.testing.assert_allclose(np.asarray(ys_fast), np.asarray(ys_ref),
                               rtol=2e-5, atol=2e-5)
