"""Persistent AOT compile cache + unified program cache (ISSUE-12).

The cold-start guarantees, each proven deterministically on CPU:

- round-trip: a compiled executable serialized into the cache loads in
  a "fresh process" (in-memory program caches + jax caches cleared)
  and serves token-identically — zero jit compiles, every resolution
  ``source="aot_cache"``;
- durability: entries publish atomically (staging suffix + os.replace,
  orphaned staging files swept), and a corrupt/truncated/foreign entry
  fails CLOSED — load returns None, the entry is deleted, the engine
  recompiles and republishes, tokens unchanged;
- keying: the environment salt (jax/jaxlib versions, backend) and the
  user salt are key inputs — a different salt misses instead of
  loading a stale binary;
- warmup: `engine.warmup()` resolves the whole closed program set, so
  traffic after warmup triggers ZERO new program-cache entries and
  zero new compiles;
- the unified program cache: one `EngineConfig.program_cache_size`
  bound for every factory (the old mix of lru 8/64), with evictions
  published to ``serving_program_cache_evictions_total`` — a silent
  eviction is a silent steady-state recompile.
"""
import pathlib

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (CompileCache, EngineConfig,
                                        InferenceEngine)
from deeplearning4j_tpu.serving.compile_cache import (
    _STAGING_SUFFIX, sweep_stray_caches)
from deeplearning4j_tpu.serving.engine import (
    DEFAULT_PROGRAM_CACHE_SIZE, _ProgramLRU, set_program_cache_size)

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


@pytest.fixture(autouse=True)
def _restore_program_cache_size():
    yield
    set_program_cache_size(DEFAULT_PROGRAM_CACHE_SIZE)


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


def _run(mesh, params, prompts, **cfg_kw):
    base = dict(decode_chunk=2, max_new_tokens=6, num_slots=4,
                backoff_base_s=0.0)
    base.update(cfg_kw)
    eng = InferenceEngine(CFG, mesh, params, EngineConfig(**base))
    hs = [eng.submit(p) for p in prompts]
    eng.run_pending()
    return eng, [h.result(0) for h in hs]


def _fresh_process():
    """Simulate a replica restart inside this process: drop the
    in-memory program caches (factory entries AND their AOT-resolved
    executables) and jax's own dispatch caches — what a new process
    starts without. The on-disk AOT cache is all that survives."""
    for c in _ProgramLRU._instances:
        c.cache_clear()
    jax.clear_caches()


def _compiles(eng, source):
    total = 0.0
    for labels, child in eng._m_compiles.collect():
        if labels[1] == source:
            total += child.value
    return int(total)


# ---------------------------------------------------------------------------
# CompileCache unit behavior
# ---------------------------------------------------------------------------

def test_store_load_roundtrip_and_atomic_publish(tmp_path):
    """A toy jitted program round-trips through the cache; the
    directory never contains a staging file after publish, and a
    pre-existing orphaned staging file is swept at construction."""
    stray = tmp_path / ("x.bin" + _STAGING_SUFFIX + "-123-9")
    stray.write_bytes(b"torn half-write")
    cache = CompileCache(tmp_path)
    assert not stray.exists(), "orphaned staging file must be swept"

    fn = jax.jit(lambda x: x * 2 + 1)
    comp = fn.lower(np.ones((4,), np.float32)).compile()
    key = cache.entry_key("toy", None, (("shape", 4),))
    assert cache.load(key) is None          # miss before store
    assert cache.store(key, comp)
    assert not any(_STAGING_SUFFIX in p.name
                   for p in tmp_path.iterdir())
    loaded = cache.load(key)
    assert loaded is not None
    np.testing.assert_array_equal(
        np.asarray(loaded(np.ones((4,), np.float32))),
        np.asarray(comp(np.ones((4,), np.float32))))
    st = cache.stats()
    assert st["stores"] == 1 and st["hits"] == 1 and st["corrupt"] == 0


def test_corrupt_entry_fails_closed_and_is_deleted(tmp_path):
    """Truncated payloads, flipped bytes, and foreign files all load
    as None (counted corrupt) and the bad entry is removed so the
    next store publishes clean."""
    cache = CompileCache(tmp_path)
    fn = jax.jit(lambda x: x + 1)
    comp = fn.lower(np.zeros((2,), np.float32)).compile()
    key = cache.entry_key("toy", None, ())
    cache.store(key, comp)
    p = cache.path(key)

    blob = p.read_bytes()
    p.write_bytes(blob[: len(blob) // 2])            # truncated
    assert cache.load(key) is None
    assert not p.exists()

    cache.store(key, comp)
    raw = bytearray(p.read_bytes())
    raw[len(raw) // 2] ^= 0xFF                       # bit flip
    p.write_bytes(bytes(raw))
    assert cache.load(key) is None

    p.write_bytes(b"not an AOT entry at all")        # foreign file
    assert cache.load(key) is None
    assert cache.stats()["corrupt"] == 3


def test_keys_are_salted_by_environment_and_user_salt(tmp_path, mesh1):
    """Same geometry, different salt (the stand-in for a different
    jax/jaxlib/backend) -> different key: an upgraded runtime misses
    instead of replaying a stale executable."""
    a = CompileCache(tmp_path, salt="jax-A")
    b = CompileCache(tmp_path, salt="jax-B")
    fields = (("bucket", 16), ("slots", 4))
    ka = a.entry_key("prefill", mesh1, fields)
    kb = b.entry_key("prefill", mesh1, fields)
    assert ka != kb
    assert ka == a.entry_key("prefill", mesh1, fields)  # stable
    assert a.entry_key("decode", mesh1, fields) != ka   # program name


def test_sweep_stray_caches(tmp_path):
    (tmp_path / "dl4j-aot-test-abc").mkdir()
    (tmp_path / "dl4j-aot-test-def").mkdir()
    (tmp_path / "unrelated").mkdir()
    n = sweep_stray_caches(root=tmp_path, prefix="dl4j-aot-test-")
    assert n == 2
    assert (tmp_path / "unrelated").exists()
    assert not (tmp_path / "dl4j-aot-test-abc").exists()


# ---------------------------------------------------------------------------
# engine integration: cold start -> warm start
# ---------------------------------------------------------------------------

def test_cold_then_warm_restart_loads_instead_of_compiling(
        tmp_path, params, mesh1):
    """The tentpole round-trip: a cold engine populates the cache
    (every resolution source="jit"); after a simulated restart the
    same config resolves its ENTIRE warmup set from disk
    (source="aot_cache", zero jit compiles) and serves byte-identical
    tokens."""
    prompts = [_prompt(6 + i, i) for i in range(5)]
    _, ref = _run(mesh1, params, prompts)

    _fresh_process()
    cold, got = _run(mesh1, params, prompts,
                     compile_cache_dir=str(tmp_path),
                     warmup_on_init=True)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
    assert cold.last_warmup["jit"] == cold.last_warmup["programs"] > 0
    assert cold.last_warmup["aot_cache"] == 0
    assert cold._aot.stats()["stores"] == cold.last_warmup["programs"]

    _fresh_process()
    warm, got2 = _run(mesh1, params, prompts,
                      compile_cache_dir=str(tmp_path),
                      warmup_on_init=True)
    for a, b in zip(ref, got2):
        np.testing.assert_array_equal(a, b)
    assert warm.last_warmup["jit"] == 0, \
        "a warm restart must not XLA-compile anything"
    assert warm.last_warmup["aot_cache"] == warm.last_warmup["programs"]


def test_corrupt_cache_entry_recompiles_token_exact(
        tmp_path, params, mesh1):
    """Corrupting one on-disk entry degrades exactly one resolution to
    a recompile (which republishes a clean entry); tokens unchanged."""
    prompts = [_prompt(7, 1)]
    _fresh_process()
    _, ref = _run(mesh1, params, prompts,
                  compile_cache_dir=str(tmp_path), warmup_on_init=True)
    victim = sorted(pathlib.Path(tmp_path).glob("*.bin"))[0]
    victim.write_bytes(victim.read_bytes()[:64])

    _fresh_process()
    eng, got = _run(mesh1, params, prompts,
                    compile_cache_dir=str(tmp_path),
                    warmup_on_init=True)
    np.testing.assert_array_equal(ref[0], got[0])
    assert eng._aot.stats()["corrupt"] == 1
    assert eng.last_warmup["jit"] == 1          # only the victim
    assert eng.last_warmup["aot_cache"] == eng.last_warmup["programs"] - 1
    assert victim.exists(), "recompile must republish the entry"


def test_warmup_makes_traffic_compile_free(tmp_path, params, mesh1):
    """After warmup() the whole mixed-length trace adds ZERO compiles
    and ZERO program-cache entries — the closed-program-set claim the
    warm-up API rests on."""
    _fresh_process()
    eng = InferenceEngine(
        CFG, mesh1, params,
        EngineConfig(decode_chunk=2, max_new_tokens=6, num_slots=4,
                     compile_cache_dir=str(tmp_path)))
    report = eng.warmup()
    assert report["programs"] > 0
    jit0, aot0 = _compiles(eng, "jit"), _compiles(eng, "aot_cache")
    sizes0 = [c.cache_info().currsize for c in _ProgramLRU._instances]
    hs = [eng.submit(_prompt(4 + 5 * i, i)) for i in range(6)]
    eng.run_pending()
    assert all(h.done() for h in hs)
    assert _compiles(eng, "jit") == jit0
    assert _compiles(eng, "aot_cache") == aot0
    assert [c.cache_info().currsize
            for c in _ProgramLRU._instances] == sizes0


def test_quantized_and_paged_geometries_roundtrip(tmp_path, params,
                                                  mesh1):
    """int8-KV and paged engines cache and reload their own program
    set (distinct keys from the float/contiguous ones), token-exact
    across the restart."""
    prompts = [_prompt(6, 2), _prompt(11, 3)]
    for kw in ({"kv_quantize": "int8"},
               {"paged": True, "page_size": 8}):
        d = tmp_path / ("-".join(sorted(kw)))
        _fresh_process()
        _, ref = _run(mesh1, params, prompts, **kw)
        _fresh_process()
        _, cold = _run(mesh1, params, prompts,
                       compile_cache_dir=str(d), warmup_on_init=True,
                       **kw)
        _fresh_process()
        warm_eng, warm = _run(mesh1, params, prompts,
                              compile_cache_dir=str(d),
                              warmup_on_init=True, **kw)
        for a, b, c in zip(ref, cold, warm):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
        assert warm_eng.last_warmup["jit"] == 0


# ---------------------------------------------------------------------------
# the unified program cache (satellite)
# ---------------------------------------------------------------------------

def test_program_cache_size_unified_and_evictions_published(
        params, mesh1):
    """Shrinking EngineConfig.program_cache_size to 2 while driving >2
    prefill-bucket geometries forces evictions: the counter publishes
    them, the caches never exceed the bound, and the engine still
    completes every request correctly."""
    # the reference engine FIRST: engine construction applies its
    # config's (process-wide) program_cache_size, so the constrained
    # engine must be built last
    ref_eng = InferenceEngine(
        CFG, mesh1, params,
        EngineConfig(decode_chunk=2, max_new_tokens=4, num_slots=2,
                     max_batch_size=2))
    eng = InferenceEngine(
        CFG, mesh1, params,
        EngineConfig(decode_chunk=2, max_new_tokens=4, num_slots=2,
                     max_batch_size=2, program_cache_size=2))
    # 3 bucket geometries (16, 32, 64) + the decode program > size 2
    outs = []
    for t0 in (8, 24, 40):
        h = eng.submit(_prompt(t0, 1))
        eng.run_pending()
        outs.append(h.result(0))
    evicted = eng.registry.get(
        "serving_program_cache_evictions").value
    assert evicted > 0, "a 2-entry cache over 4+ geometries must evict"
    for c in _ProgramLRU._instances:
        assert c.cache_info().currsize <= 2
        assert c.cache_info().maxsize == 2
    # behavior unaffected: an unconstrained engine agrees byte-for-byte
    set_program_cache_size(DEFAULT_PROGRAM_CACHE_SIZE)
    for t0, want in zip((8, 24, 40), outs):
        h = ref_eng.submit(_prompt(t0, 1))
        ref_eng.run_pending()
        np.testing.assert_array_equal(h.result(0), want)


def test_program_cache_size_validates():
    with pytest.raises(ValueError, match="program_cache_size"):
        set_program_cache_size(0)


def test_compile_metrics_have_samples(params, mesh1):
    """serving_compiles_total{program,source} and
    serving_compile_seconds{program} carry samples on a plain engine —
    recompiles are observable without any cache configured."""
    _fresh_process()
    eng, _ = _run(mesh1, params, [_prompt(6, 4)])
    assert _compiles(eng, "jit") >= 2           # prefill + decode
    fams = {labels[0] for labels, _ in eng._m_compile_seconds.collect()}
    assert {"prefill", "decode"} <= fams
