"""Config DSL: builder, shape inference, preprocessor insertion, JSON
round-trip (reference test analog: deeplearning4j-core/src/test/java/org/
deeplearning4j/nn/conf/ serialization tests)."""
import json
import numpy as np

from deeplearning4j_tpu import (MultiLayerConfiguration,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnFlatToCnnPreProcessor, CnnToFeedForwardPreProcessor)
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          GravesLSTM, OutputLayer,
                                          SubsamplingLayer)


def lenet_conf():
    return (NeuralNetConfiguration(seed=7, updater="adam",
                                   learning_rate=1e-3,
                                   weight_init="xavier")
            .list(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                   activation="relu"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                  ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                   activation="relu"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                  DenseLayer(n_out=500, activation="relu"),
                  OutputLayer(n_out=10, activation="softmax",
                              loss_function="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1)))


def test_shape_inference_lenet():
    conf = lenet_conf()
    conf.resolve_shapes()
    # conv1 gets n_in from input channels
    assert conf.layers[0].n_in == 1
    # conv2 n_in = conv1 n_out
    assert conf.layers[2].n_in == 20
    # dense n_in = 4*4*50 after two conv(5x5,valid)+pool(2x2) stages
    assert conf.layers[4].n_in == 4 * 4 * 50
    assert conf.layers[5].n_in == 500
    # preprocessors auto-inserted: flat->cnn at 0, cnn->ff at 4
    assert isinstance(conf.input_preprocessors["0"],
                      CnnFlatToCnnPreProcessor)
    assert isinstance(conf.input_preprocessors["4"],
                      CnnToFeedForwardPreProcessor)


def test_global_defaults_applied():
    conf = (NeuralNetConfiguration(activation="tanh", weight_init="relu",
                                   l2=1e-4, learning_rate=0.05)
            .list(DenseLayer(n_in=4, n_out=3),
                  OutputLayer(n_in=3, n_out=2, activation="softmax")))
    assert conf.layers[0].activation == "tanh"
    assert conf.layers[0].weight_init == "relu"
    assert conf.layers[0].l2 == 1e-4
    assert conf.layers[0].learning_rate == 0.05
    # explicit layer setting wins over global
    assert conf.layers[1].activation == "softmax"


def test_json_roundtrip():
    conf = lenet_conf()
    conf.resolve_shapes()
    js = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(js)
    assert len(conf2.layers) == len(conf.layers)
    assert conf2.layers[0].n_out == 20
    assert conf2.layers[0].kernel_size == [5, 5]
    assert conf2.training.updater == "adam"
    assert conf2.training.learning_rate == 1e-3
    # round-trip again: stable
    assert conf2.to_json() == MultiLayerConfiguration.from_json(js).to_json()


def test_json_roundtrip_rnn():
    conf = (NeuralNetConfiguration(seed=3)
            .list(GravesLSTM(n_in=10, n_out=8, activation="tanh"),
                  OutputLayer(n_in=8, n_out=4, activation="softmax")))
    js = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2.layers[0].n_out == 8
    assert conf2.layers[0].peephole is True


def test_tbptt_config():
    conf = (NeuralNetConfiguration()
            .list(GravesLSTM(n_in=5, n_out=6),
                  OutputLayer(n_in=6, n_out=2))
            .backprop_type_tbptt(10, 10))
    assert conf.backprop_type == "tbptt"
    js = conf.to_json()
    assert MultiLayerConfiguration.from_json(js).tbptt_fwd_length == 10


def test_every_registered_layer_roundtrips_json():
    """Exhaustive serde coverage: every @register'ed Layer subclass
    survives JSON round-trip with non-default field values (the
    reference's polymorphic-subtype Jackson round-trip tests,
    MultiLayerConfiguration.fromJson:122, across ALL layer configs)."""
    import dataclasses
    from deeplearning4j_tpu.nn.conf import serde
    from deeplearning4j_tpu.nn.layers.base import Layer

    skipped = set()
    checked = 0
    for name, cls in sorted(serde._REGISTRY.items()):
        if not (isinstance(cls, type) and issubclass(cls, Layer)
                and dataclasses.is_dataclass(cls)):
            skipped.add(name)
            continue
        NONDEFAULT = {"n_in": 7, "n_out": 9, "dropout": 0.25,
                      "activation": "elu", "weight_init": "relu",
                      "l1": 0.01, "l2": 0.02, "bias_init": 0.3,
                      "name": "lyr"}
        kwargs = {f.name: NONDEFAULT[f.name]
                  for f in dataclasses.fields(cls)
                  if f.name in NONDEFAULT}
        layer = cls(**kwargs)
        d = serde.to_dict(layer)
        back = serde.from_dict(json.loads(json.dumps(d)))
        assert type(back) is cls, name
        for f in dataclasses.fields(cls):
            got = getattr(back, f.name)
            want = getattr(layer, f.name)
            if isinstance(want, tuple):
                got = tuple(got) if isinstance(got, list) else got
            assert _eq(got, want), (name, f.name, got, want)
        checked += 1
    assert checked >= 25, (checked, skipped)

    # wrapper-layer nesting: FrozenLayer with a REAL inner layer must
    # reconstruct the nested dataclass, not a dict
    from deeplearning4j_tpu.nn.layers import DenseLayer
    from deeplearning4j_tpu.nn.layers.misc import FrozenLayer
    fl = FrozenLayer(inner=DenseLayer(n_in=7, n_out=9, activation="elu"))
    back = serde.from_dict(json.loads(json.dumps(serde.to_dict(fl))))
    assert isinstance(back, FrozenLayer)
    assert isinstance(back.inner, DenseLayer)
    assert back.inner.activation == "elu" and back.inner.n_out == 9


def _eq(a, b):
    import dataclasses
    if dataclasses.is_dataclass(a) and dataclasses.is_dataclass(b):
        return type(a) is type(b) and all(
            _eq(getattr(a, f.name), getattr(b, f.name))
            for f in dataclasses.fields(a))
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_eq(x, y) for x, y in zip(a, b))
    return a == b


def test_yaml_roundtrip():
    """YAML round-trip parity with JSON (reference:
    MultiLayerConfiguration.java:79 toYaml / :108-126 both formats)."""
    conf = lenet_conf()
    conf.resolve_shapes()
    ym = conf.to_yaml()
    conf2 = MultiLayerConfiguration.from_yaml(ym)
    assert len(conf2.layers) == len(conf.layers)
    assert conf2.layers[0].n_out == 20
    assert conf2.layers[0].kernel_size == [5, 5]
    assert conf2.training.updater == "adam"
    # YAML and JSON round-trips agree exactly
    assert conf2.to_json() == MultiLayerConfiguration.from_json(
        conf.to_json()).to_json()
    # stable across a second YAML round-trip
    assert MultiLayerConfiguration.from_yaml(conf2.to_yaml()).to_yaml() == ym
    # wrong-type document fails loudly
    import pytest
    with pytest.raises(ValueError):
        MultiLayerConfiguration.from_yaml("just: a\nplain: mapping\n")


def test_yaml_roundtrip_computation_graph():
    from deeplearning4j_tpu.nn.conf.configuration import (
        ComputationGraphConfiguration, GraphVertexSpec)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    cg = ComputationGraphConfiguration(
        network_inputs=["in"], network_outputs=["out"],
        vertices={
            "h": GraphVertexSpec(DenseLayer(n_in=4, n_out=8), ["in"]),
            "out": GraphVertexSpec(
                OutputLayer(n_in=8, n_out=2, activation="softmax"), ["h"]),
        })
    back = ComputationGraphConfiguration.from_yaml(cg.to_yaml())
    assert isinstance(back, ComputationGraphConfiguration)
    assert back.vertices["h"].vertex.n_out == 8
    assert back.vertices["out"].inputs == ["h"]
    assert back.to_json() == cg.to_json()


def test_every_registered_layer_roundtrips_yaml():
    """YAML parity with the exhaustive JSON layer-serde suite: every
    @register'ed Layer subclass survives to_yaml/from_yaml with
    non-default field values."""
    import dataclasses
    from deeplearning4j_tpu.nn.conf import serde
    from deeplearning4j_tpu.nn.layers.base import Layer

    checked = 0
    for name, cls in sorted(serde._REGISTRY.items()):
        if not (isinstance(cls, type) and issubclass(cls, Layer)
                and dataclasses.is_dataclass(cls)):
            continue
        NONDEFAULT = {"n_in": 7, "n_out": 9, "dropout": 0.25,
                      "activation": "elu", "weight_init": "relu",
                      "l1": 0.01, "l2": 0.02, "bias_init": 0.3,
                      "name": "lyr"}
        kwargs = {f.name: NONDEFAULT[f.name]
                  for f in dataclasses.fields(cls)
                  if f.name in NONDEFAULT}
        layer = cls(**kwargs)
        back = serde.from_yaml(serde.to_yaml(layer))
        assert type(back) is cls, name
        for f in dataclasses.fields(cls):
            got = getattr(back, f.name)
            want = getattr(layer, f.name)
            if isinstance(want, tuple):
                got = tuple(got) if isinstance(got, list) else got
            assert _eq(got, want), (name, f.name, got, want)
        checked += 1
    assert checked >= 25, checked
