"""Gradient checks — the correctness backbone (reference test analog:
deeplearning4j-core/src/test/.../gradientcheck/{GradientCheckTests,
CNNGradientCheckTest,BNGradientCheckTest,...}.java, SURVEY.md §4). Runs in
float64 for reference-grade precision (ε=1e-6, max rel error 1e-3)."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (BatchNormalization,
                                          ConvolutionLayer, DenseLayer,
                                          GlobalPoolingLayer, GravesLSTM,
                                          GravesBidirectionalLSTM,
                                          OutputLayer, RnnOutputLayer,
                                          SubsamplingLayer)

jax.config.update("jax_enable_x64", True)


def _check(conf, x, y, **kw):
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, x, y, print_results=True, **kw)


RNG = np.random.RandomState(12345)


def test_gradcheck_mlp():
    x = RNG.randn(6, 4)
    y = np.eye(3)[RNG.randint(0, 3, 6)]
    for loss, act in [("mcxent", "softmax"), ("mse", "identity"),
                      ("xent", "sigmoid")]:
        yy = y if loss != "xent" else (y > 0).astype(float)
        conf = (NeuralNetConfiguration(seed=42, activation="tanh",
                                       dtype="float64")
                .list(DenseLayer(n_in=4, n_out=5),
                      OutputLayer(n_in=5, n_out=3, activation=act,
                                  loss_function=loss)))
        _check(conf, x, yy)


def test_gradcheck_mlp_l1_l2():
    x = RNG.randn(5, 4)
    y = np.eye(3)[RNG.randint(0, 3, 5)]
    conf = (NeuralNetConfiguration(seed=42, activation="sigmoid", l1=0.01,
                                   l2=0.02, dtype="float64")
            .list(DenseLayer(n_in=4, n_out=6),
                  OutputLayer(n_in=6, n_out=3, activation="softmax")))
    _check(conf, x, y)


def test_gradcheck_cnn():
    x = RNG.randn(3, 6 * 6)
    y = np.eye(2)[RNG.randint(0, 2, 3)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(ConvolutionLayer(n_out=3, kernel_size=(2, 2),
                                   activation="tanh"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                   pooling_type="avg"),
                  OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.convolutional_flat(6, 6, 1)))
    _check(conf, x, y)


def test_gradcheck_cnn_maxpool():
    x = RNG.randn(2, 6 * 6)
    y = np.eye(2)[RNG.randint(0, 2, 2)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                   activation="sigmoid"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                   pooling_type="max"),
                  OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.convolutional_flat(6, 6, 1)))
    _check(conf, x, y)


def test_gradcheck_batchnorm():
    x = RNG.randn(8, 5)
    y = np.eye(3)[RNG.randint(0, 3, 8)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(DenseLayer(n_in=5, n_out=6, activation="tanh"),
                  BatchNormalization(),
                  OutputLayer(n_in=6, n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(5)))
    _check(conf, x, y)


def test_gradcheck_lstm():
    x = RNG.randn(3, 6, 4)
    y = np.zeros((3, 6, 2))
    y[np.arange(3), :, RNG.randint(0, 2, 3)] = 1.0
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesLSTM(n_in=4, n_out=5, activation="tanh"),
                  RnnOutputLayer(n_in=5, n_out=2, activation="softmax")))
    _check(conf, x, y)


def test_gradcheck_bidirectional_lstm():
    x = RNG.randn(2, 5, 3)
    y = np.zeros((2, 5, 2))
    y[np.arange(2), :, RNG.randint(0, 2, 2)] = 1.0
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesBidirectionalLSTM(n_in=3, n_out=4,
                                          activation="tanh"),
                  RnnOutputLayer(n_in=4, n_out=2, activation="softmax")))
    _check(conf, x, y)


def test_gradcheck_masked_rnn():
    x = RNG.randn(3, 5, 4)
    y = np.zeros((3, 5, 2))
    y[np.arange(3), :, RNG.randint(0, 2, 3)] = 1.0
    mask = np.array([[1, 1, 1, 1, 1], [1, 1, 1, 0, 0], [1, 0, 0, 0, 0]],
                    dtype=np.float64)
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesLSTM(n_in=4, n_out=4, activation="tanh"),
                  RnnOutputLayer(n_in=4, n_out=2, activation="softmax")))
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, x, y, mask=mask, print_results=True)


def test_gradcheck_global_pooling():
    x = RNG.randn(3, 6, 4)
    y = np.eye(2)[RNG.randint(0, 2, 3)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesLSTM(n_in=4, n_out=5, activation="tanh"),
                  GlobalPoolingLayer(pooling_type="avg"),
                  OutputLayer(n_in=5, n_out=2, activation="softmax")))
    _check(conf, x, y)
