"""Gradient checks — the correctness backbone (reference test analog:
deeplearning4j-core/src/test/.../gradientcheck/{GradientCheckTests,
CNNGradientCheckTest,BNGradientCheckTest,...}.java, SURVEY.md §4). Runs in
float64 for reference-grade precision (ε=1e-6, max rel error 1e-3)."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (BatchNormalization,
                                          ConvolutionLayer, DenseLayer,
                                          GlobalPoolingLayer, GravesLSTM,
                                          GravesBidirectionalLSTM,
                                          OutputLayer, RnnOutputLayer,
                                          SubsamplingLayer)

jax.config.update("jax_enable_x64", True)


def _check(conf, x, y, **kw):
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, x, y, print_results=True, **kw)


RNG = np.random.RandomState(12345)


def test_gradcheck_mlp():
    x = RNG.randn(6, 4)
    y = np.eye(3)[RNG.randint(0, 3, 6)]
    for loss, act in [("mcxent", "softmax"), ("mse", "identity"),
                      ("xent", "sigmoid")]:
        yy = y if loss != "xent" else (y > 0).astype(float)
        conf = (NeuralNetConfiguration(seed=42, activation="tanh",
                                       dtype="float64")
                .list(DenseLayer(n_in=4, n_out=5),
                      OutputLayer(n_in=5, n_out=3, activation=act,
                                  loss_function=loss)))
        _check(conf, x, yy)


def test_gradcheck_mlp_l1_l2():
    x = RNG.randn(5, 4)
    y = np.eye(3)[RNG.randint(0, 3, 5)]
    conf = (NeuralNetConfiguration(seed=42, activation="sigmoid", l1=0.01,
                                   l2=0.02, dtype="float64")
            .list(DenseLayer(n_in=4, n_out=6),
                  OutputLayer(n_in=6, n_out=3, activation="softmax")))
    _check(conf, x, y)


def test_gradcheck_cnn():
    x = RNG.randn(3, 6 * 6)
    y = np.eye(2)[RNG.randint(0, 2, 3)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(ConvolutionLayer(n_out=3, kernel_size=(2, 2),
                                   activation="tanh"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                   pooling_type="avg"),
                  OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.convolutional_flat(6, 6, 1)))
    _check(conf, x, y)


def test_gradcheck_cnn_maxpool():
    x = RNG.randn(2, 6 * 6)
    y = np.eye(2)[RNG.randint(0, 2, 2)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                   activation="sigmoid"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                   pooling_type="max"),
                  OutputLayer(n_out=2, activation="softmax"))
            .set_input_type(InputType.convolutional_flat(6, 6, 1)))
    _check(conf, x, y)


def test_gradcheck_batchnorm():
    x = RNG.randn(8, 5)
    y = np.eye(3)[RNG.randint(0, 3, 8)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(DenseLayer(n_in=5, n_out=6, activation="tanh"),
                  BatchNormalization(),
                  OutputLayer(n_in=6, n_out=3, activation="softmax"))
            .set_input_type(InputType.feed_forward(5)))
    _check(conf, x, y)


def test_gradcheck_lstm():
    x = RNG.randn(3, 6, 4)
    y = np.zeros((3, 6, 2))
    y[np.arange(3), :, RNG.randint(0, 2, 3)] = 1.0
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesLSTM(n_in=4, n_out=5, activation="tanh"),
                  RnnOutputLayer(n_in=5, n_out=2, activation="softmax")))
    _check(conf, x, y)


def test_gradcheck_bidirectional_lstm():
    x = RNG.randn(2, 5, 3)
    y = np.zeros((2, 5, 2))
    y[np.arange(2), :, RNG.randint(0, 2, 2)] = 1.0
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesBidirectionalLSTM(n_in=3, n_out=4,
                                          activation="tanh"),
                  RnnOutputLayer(n_in=4, n_out=2, activation="softmax")))
    _check(conf, x, y)


def test_gradcheck_masked_rnn():
    x = RNG.randn(3, 5, 4)
    y = np.zeros((3, 5, 2))
    y[np.arange(3), :, RNG.randint(0, 2, 3)] = 1.0
    mask = np.array([[1, 1, 1, 1, 1], [1, 1, 1, 0, 0], [1, 0, 0, 0, 0]],
                    dtype=np.float64)
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesLSTM(n_in=4, n_out=4, activation="tanh"),
                  RnnOutputLayer(n_in=4, n_out=2, activation="softmax")))
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, x, y, mask=mask, print_results=True)


def test_gradcheck_global_pooling():
    x = RNG.randn(3, 6, 4)
    y = np.eye(2)[RNG.randint(0, 2, 3)]
    conf = (NeuralNetConfiguration(seed=42, dtype="float64")
            .list(GravesLSTM(n_in=4, n_out=5, activation="tanh"),
                  GlobalPoolingLayer(pooling_type="avg"),
                  OutputLayer(n_in=5, n_out=2, activation="softmax")))
    _check(conf, x, y)


def test_gradcheck_cnn1d():
    """Reference analog: CNN1DGradientCheckTest.java."""
    from deeplearning4j_tpu.nn.layers import (Convolution1DLayer,
                                              Subsampling1DLayer)
    x = RNG.randn(3, 10, 4).astype(np.float64)  # [B, T, C]
    y = np.eye(2)[RNG.randint(0, 2, (3, 10))].astype(np.float64)
    conf = (NeuralNetConfiguration(seed=1, activation="tanh",
                                   dtype="float64")
            .list(Convolution1DLayer(n_in=4, n_out=5, kernel_size=3,
                                     convolution_mode="same"),
                  Subsampling1DLayer(kernel_size=2, stride=1,
                                     convolution_mode="same"),
                  RnnOutputLayer(n_in=5, n_out=2, activation="softmax",
                                 loss_function="mcxent")))
    _check(conf, x, y)


def test_gradcheck_lrn():
    """Reference analog: LRNGradientCheckTests.java."""
    from deeplearning4j_tpu.nn.layers import LocalResponseNormalization
    x = RNG.randn(2, 5, 5, 3).astype(np.float64)
    y = np.eye(2)[RNG.randint(0, 2, 2)].astype(np.float64)
    conf = (NeuralNetConfiguration(seed=2, activation="tanh",
                                   dtype="float64")
            .list(ConvolutionLayer(n_out=4, kernel_size=(2, 2)),
                  LocalResponseNormalization(),
                  DenseLayer(n_out=6, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax",
                              loss_function="mcxent"))
            .set_input_type(InputType.convolutional(5, 5, 3)))
    _check(conf, x, y)


@pytest.mark.parametrize("loss,act,regression", [
    ("mse", "identity", True),
    ("mae", "identity", True),
    ("l1", "identity", True),
    ("l2", "identity", True),
    ("xent", "sigmoid", False),
    ("mcxent", "softmax", False),
    ("negativeloglikelihood", "softmax", False),
    ("kl_divergence", "softmax", False),
    ("poisson", "softplus", True),
    ("msle", "softplus", True),
    ("squared_hinge", "identity", False),
    ("cosine_proximity", "identity", True),
])
def test_gradcheck_loss_functions(loss, act, regression):
    """Reference analog: LossFunctionGradientCheck.java — every loss
    function paired with a compatible output activation."""
    n, f, c = 4, 5, 3
    x = RNG.randn(n, f).astype(np.float64)
    if regression:
        y = RNG.randn(n, c).astype(np.float64)
        if loss in ("msle", "poisson"):
            y = np.abs(y) + 0.1
    elif loss in ("squared_hinge",):
        y = (np.eye(c)[RNG.randint(0, c, n)] * 2 - 1).astype(np.float64)
    else:
        y = np.eye(c)[RNG.randint(0, c, n)].astype(np.float64)
    conf = (NeuralNetConfiguration(seed=4, activation="tanh",
                                   dtype="float64")
            .list(DenseLayer(n_in=f, n_out=8),
                  OutputLayer(n_in=8, n_out=c, activation=act,
                              loss_function=loss)))
    _check(conf, x, y)


def test_gradcheck_computation_graph_vertices():
    """Reference analog: GradientCheckTestsComputationGraph.java — merge
    + elementwise vertices in a DAG."""
    from deeplearning4j_tpu.gradientcheck import check_gradients
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    from deeplearning4j_tpu.nn.graph.vertices import (ElementWiseVertex,
                                                      MergeVertex)
    x = RNG.randn(3, 6).astype(np.float64)
    y = np.eye(2)[RNG.randint(0, 2, 3)].astype(np.float64)
    conf = (NeuralNetConfiguration(seed=5, activation="tanh",
                                   dtype="float64")
            .graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_in=6, n_out=5), "in")
            .add_layer("b", DenseLayer(n_in=6, n_out=5), "in")
            .add_vertex("sum", ElementWiseVertex(op="add"), "a", "b")
            .add_vertex("cat", MergeVertex(), "a", "sum")
            .add_layer("out", OutputLayer(n_in=10, n_out=2,
                                          activation="softmax",
                                          loss_function="mcxent"), "cat")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    assert check_gradients(net, x, y, print_results=True)


def test_gradcheck_multi_head_attention():
    """Net-new attention DSL layers get the same gradient-check backbone
    as every reference layer family."""
    from deeplearning4j_tpu.nn.layers import (MultiHeadAttention,
                                              RnnOutputLayer)
    x = RNG.randn(2, 6, 8).astype(np.float64)
    y = np.eye(3)[RNG.randint(0, 3, (2, 6))].astype(np.float64)
    conf = (NeuralNetConfiguration(seed=3, dtype="float64")
            .list(MultiHeadAttention(n_in=8, n_out=8, n_heads=2,
                                     causal=True),
                  RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                 loss_function="mcxent")))
    _check(conf, x, y)


def test_gradcheck_transformer_block_and_layernorm():
    from deeplearning4j_tpu.nn.layers import (LayerNormalization,
                                              RnnOutputLayer,
                                              TransformerBlock)
    x = RNG.randn(2, 5, 8).astype(np.float64)
    y = np.eye(2)[RNG.randint(0, 2, (2, 5))].astype(np.float64)
    conf = (NeuralNetConfiguration(seed=4, dtype="float64")
            .list(TransformerBlock(n_in=8, n_heads=2),
                  LayerNormalization(),
                  RnnOutputLayer(n_in=8, n_out=2, activation="softmax",
                                 loss_function="mcxent")))
    _check(conf, x, y)
