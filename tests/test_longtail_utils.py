"""Tests for the SURVEY §2 long-tail utilities added after the grep
audit: stopwords, berkeley counters/queues, time-series + masked
reductions, QuadTree, MagicQueue/AsyncIterator, MLLibUtil/SparkUtils
analogs, VanillaStatsStorageRouter, distributed SequenceVectors,
Word2VecDataSetIterator."""
import numpy as np
import pytest


def test_stopwords():
    from deeplearning4j_tpu.nlp.stopwords import StopWords, is_stop_word

    words = StopWords.get_stop_words()
    assert "the" in words and "and" in words
    assert is_stop_word("The") and not is_stop_word("tensor")
    assert StopWords.get_stop_words() is StopWords.get_stop_words()


def test_berkeley_counter_and_queue():
    from deeplearning4j_tpu.util.berkeley import (Counter, CounterMap, Pair,
                                                  PriorityQueue, Triple)

    c = Counter()
    c.increment_all(["a", "b", "a", "c", "a"])
    assert c.get_count("a") == 3 and c.argmax() == "a"
    assert c.total_count() == 5
    c.normalize()
    assert abs(c.total_count() - 1.0) < 1e-12
    assert c.keys_sorted_by_count()[0] == "a"

    cm = CounterMap()
    cm.increment_count("x", "y", 2.0)
    cm.increment_count("x", "z")
    assert cm.get_count("x", "y") == 2.0
    assert cm.get_counter("x").argmax() == "y"
    assert cm.total_count() == 3.0

    pq = PriorityQueue()
    pq.put("low", 1.0)
    pq.put("high", 9.0)
    pq.put("mid", 5.0)
    assert pq.peek() == "high" and pq.get_priority() == 9.0
    assert list(pq) == ["high", "mid", "low"]

    p = Pair(1, "a")
    assert p.reverse().first == "a" and tuple(p) == (1, "a")
    assert hash(Triple(1, 2, 3)) == hash(Triple(1, 2, 3))


def test_timeseries_reshapes_and_moving_average():
    from deeplearning4j_tpu.util import timeseries as ts

    x = np.arange(1.0, 7.0)  # 1..6
    ma = np.asarray(ts.moving_average(x, 3))
    np.testing.assert_allclose(ma, [2.0, 3.0, 4.0, 5.0])

    arr = np.arange(24.0).reshape(2, 3, 4)  # [B=2, T=3, F=4]
    flat = np.asarray(ts.reshape_3d_to_2d(arr))
    assert flat.shape == (6, 4)
    back = np.asarray(ts.reshape_2d_to_3d(flat, 2))
    np.testing.assert_array_equal(back, arr)

    mask = np.array([[1.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
    v = np.asarray(ts.reshape_time_series_mask_to_vector(mask))
    assert v.shape == (6, 1)
    m2 = np.asarray(ts.reshape_vector_to_time_series_mask(v, 2))
    np.testing.assert_array_equal(m2, mask)


def test_masked_pooling_matches_manual():
    from deeplearning4j_tpu.util.timeseries import (
        masked_pooling_convolution, masked_pooling_time_series)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)
    mask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)

    mx = np.asarray(masked_pooling_time_series("max", x, mask))
    np.testing.assert_allclose(mx[0], x[0, :3].max(0), rtol=1e-6)
    np.testing.assert_allclose(mx[1], x[1].max(0), rtol=1e-6)

    avg = np.asarray(masked_pooling_time_series("avg", x, mask))
    np.testing.assert_allclose(avg[0], x[0, :3].mean(0), rtol=1e-5)

    s = np.asarray(masked_pooling_time_series("sum", x, mask))
    np.testing.assert_allclose(s[0], x[0, :3].sum(0), rtol=1e-5)

    pn = np.asarray(masked_pooling_time_series("pnorm", x, mask, pnorm=2))
    np.testing.assert_allclose(
        pn[0], np.sqrt((np.abs(x[0, :3]) ** 2).sum(0)), rtol=1e-5)

    # CNN variant: NHWC with a [B,H,W] mask
    img = rng.normal(size=(1, 4, 4, 2)).astype(np.float32)
    imask = np.zeros((1, 4, 4), np.float32)
    imask[0, :2, :2] = 1.0
    mavg = np.asarray(masked_pooling_convolution("avg", img, imask))
    np.testing.assert_allclose(mavg[0], img[0, :2, :2].reshape(-1, 2).mean(0),
                               rtol=1e-5)


def test_quadtree_structure_and_forces():
    from deeplearning4j_tpu.clustering.quadtree import QuadTree

    rng = np.random.default_rng(42)
    pts = rng.normal(size=(64, 2))
    tree = QuadTree(pts)
    assert tree.cum_size == 64
    np.testing.assert_allclose(tree.center_of_mass, pts.mean(0), atol=1e-8)
    assert tree.depth() > 1

    # theta=0 forces exact evaluation -> matches brute-force repulsion
    i = 7
    neg = np.zeros(2)
    sum_q = tree.compute_non_edge_forces(i, 0.0, neg)
    diff = pts[i] - pts  # [n, 2]
    d2 = (diff ** 2).sum(1)
    q = 1.0 / (1.0 + d2)
    q[i] = 0.0
    expect_sum_q = q.sum()
    expect_neg = (q[:, None] ** 2 * diff).sum(0)
    np.testing.assert_allclose(sum_q, expect_sum_q, rtol=1e-8)
    np.testing.assert_allclose(neg, expect_neg, rtol=1e-8)

    # theta>0 approximates it
    neg_a = np.zeros(2)
    sq_a = tree.compute_non_edge_forces(i, 0.5, neg_a)
    assert abs(sq_a - expect_sum_q) / expect_sum_q < 0.15


def test_magic_queue_round_robin_and_global():
    from deeplearning4j_tpu.datasets.iterators import DataSet
    from deeplearning4j_tpu.parallel.magicqueue import (AsyncIterator,
                                                        MagicQueue)

    q = MagicQueue(num_devices=4)
    for i in range(8):
        q.put(DataSet(np.full((2, 3), i, np.float32),
                      np.full((2, 1), i, np.float32)))
    assert q.size() == 2  # two complete rounds
    g = q.next_global()
    assert g.features.shape == (8, 3)  # one batch from every bucket
    assert sorted(set(g.features[:, 0])) == [0.0, 1.0, 2.0, 3.0]
    # device 0's remaining batch is the round-2 one
    assert q.poll(0).features[0, 0] == 4
    assert q.poll(0) is None
    assert not q.is_empty()

    items = list(AsyncIterator(range(10), buffer_size=3))
    assert items == list(range(10))

    def boom():
        yield 1
        raise RuntimeError("worker died")

    it = AsyncIterator(boom())
    assert next(it) == 1
    with pytest.raises(RuntimeError):
        next(it)


def test_mllib_util_and_spark_utils(tmp_path):
    from deeplearning4j_tpu.scaleout.util import (
        from_labeled_point, pad_to_multiple, read_object_from_file,
        repartition_balanced, split_data, to_labeled_point,
        write_object_to_file)
    from deeplearning4j_tpu.datasets.iterators import DataSet

    feats = np.arange(12.0).reshape(6, 2)
    labels = np.eye(3)[[0, 1, 2, 0, 1, 2]]
    pts = to_labeled_point(feats, labels)
    assert [p.label for p in pts] == [0, 1, 2, 0, 1, 2]
    ds = from_labeled_point(pts, 3)
    np.testing.assert_array_equal(ds.features, feats)
    np.testing.assert_array_equal(ds.labels, labels)

    parts = repartition_balanced(feats, labels, 4)
    sizes = [p[0].shape[0] for p in parts]
    assert sum(sizes) == 6 and max(sizes) - min(sizes) <= 1

    f, l, n = pad_to_multiple(feats, labels, 4)
    assert f.shape[0] == 8 and n == 6
    np.testing.assert_array_equal(f[6], f[5])

    datasets = [DataSet(feats[i:i + 1], labels[i:i + 1]) for i in range(6)]
    train, test = split_data(datasets, 2 / 3, seed=1)
    assert len(train) == 4 and len(test) == 2

    path = str(tmp_path / "obj.pkl")
    write_object_to_file(path, {"a": 1})
    assert read_object_from_file(path) == {"a": 1}


def test_vanilla_stats_storage_router():
    from deeplearning4j_tpu.scaleout.listeners import (
        VanillaStatsStorageRouter)
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage, Persistable

    router = VanillaStatsStorageRouter()
    rec = Persistable(session_id="s1", type_id="t", worker_id="w0",
                      timestamp=1.0, score=0.5)
    router.put_update(rec)
    router.put_static_info(Persistable(session_id="s1", type_id="t",
                                       worker_id="w0", timestamp=0.0))
    assert len(router.updates) == 1
    storage = InMemoryStatsStorage()
    moved = router.drain_to(storage)
    assert moved == 2
    assert router.updates == [] and router.static_info == []
    assert "s1" in storage.list_session_ids()


def test_distributed_sequencevectors_vocab_and_fit():
    from deeplearning4j_tpu.scaleout.sequencevectors import (
        SparkWord2Vec, count_partition, merge_counters)
    from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory

    corpus = ["the cat sat on the mat",
              "the dog sat on the log",
              "cats and dogs are animals",
              "the cat chased the dog"] * 6
    tok = DefaultTokenizerFactory()
    c1 = count_partition(corpus[:12], tok)
    c2 = count_partition(corpus[12:], tok)
    merged = merge_counters([c1, c2])
    assert merged["the"] == c1["the"] + c2["the"]

    w2v = SparkWord2Vec(sentences=corpus, num_partitions=3, layer_size=16,
                        window=2, epochs=2, negative=3, seed=5,
                        min_word_frequency=1)
    w2v.fit()
    assert w2v.vocab.contains_word("cat")
    assert w2v.word_vector("cat").shape == (16,)
    assert -1.0 <= w2v.similarity("cat", "dog") <= 1.0


def test_word2vec_dataset_iterator():
    from deeplearning4j_tpu.nlp.dataset_iterators import (
        Word2VecDataSetIterator, windows)
    from deeplearning4j_tpu.nlp.sentenceiterator import (LabelAwareIterator,
                                                         LabelledDocument)
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    ws = windows(["a", "b", "c"], 3)
    assert len(ws) == 3
    assert ws[0].get_words() == ["<s>", "a", "b"]
    assert ws[2].get_words() == ["b", "c", "</s>"]

    sents = ["good great fine nice good", "bad awful poor bad sad"] * 4
    vec = Word2Vec(sentences=sents, layer_size=8, window=2, epochs=2,
                   min_word_frequency=1, seed=3)
    vec.fit()

    docs = [LabelledDocument("good great fine", ["pos"]),
            LabelledDocument("bad awful poor", ["neg"])]
    it = Word2VecDataSetIterator(vec, LabelAwareIterator(docs),
                                 labels=["pos", "neg"], batch=4,
                                 window_size=3)
    assert it.num_examples() == 6
    assert it.input_columns() == 3 * 8
    batches = list(it)
    assert batches[0].features.shape == (4, 24)
    assert batches[1].features.shape == (2, 24)
    # every window of doc 0 is labelled pos
    np.testing.assert_array_equal(batches[0].labels[0], [1.0, 0.0])
    # featurization uses real vectors: the centre word's slice is non-zero
    assert np.abs(batches[0].features[1, 8:16]).sum() > 0


def test_magic_queue_partial_round_restores_items():
    import queue as _queue

    from deeplearning4j_tpu.parallel.magicqueue import (AsyncIterator,
                                                        MagicQueue)

    q = MagicQueue(num_devices=4)
    for i in range(2):  # only half a round
        q.put(i)
    with pytest.raises(_queue.Empty):
        q.next_global()
    # nothing lost: both items still pollable from their buckets
    assert q.poll(0) == 0 and q.poll(1) == 1

    # exhausted AsyncIterator keeps raising StopIteration
    it = AsyncIterator([])
    with pytest.raises(StopIteration):
        next(it)
    assert next(it, "sentinel") == "sentinel"
