"""Early stopping tests (reference test analog:
deeplearning4j-core/src/test/java/org/deeplearning4j/earlystopping/
TestEarlyStopping.java)."""
import numpy as np

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.iterators import (BaseDatasetIterator,
                                                   DataSet)
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InMemoryModelSaver, InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer


def _net(lr=0.05):
    conf = (NeuralNetConfiguration(seed=1, updater="adam", learning_rate=lr)
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax",
                              loss_function="mcxent")))
    return MultiLayerNetwork(conf).init()


def _iter(rng, n=60, batch=20):
    x = rng.rand(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return BaseDatasetIterator(x, y, batch_size=batch)


def test_max_epochs_termination(rng):
    it = _iter(rng)
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
        score_calculator=DataSetLossCalculator(_iter(rng)),
        model_saver=InMemoryModelSaver())
    result = EarlyStoppingTrainer(es, _net(), it).fit()
    assert result.termination_reason == "EpochTerminationCondition"
    assert result.total_epochs == 5
    assert result.best_model is not None
    assert len(result.score_vs_epoch) == 5


def test_score_improvement_patience(rng):
    it = _iter(rng)
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(3),
            MaxEpochsTerminationCondition(200)],
        score_calculator=DataSetLossCalculator(_iter(rng)))
    # lr=0 -> no improvement ever -> stops after patience epochs
    result = EarlyStoppingTrainer(es, _net(lr=0.0), it).fit()
    assert result.termination_reason == "EpochTerminationCondition"
    assert "ScoreImprovement" in result.termination_details
    assert result.total_epochs <= 5


def test_max_time_termination(rng):
    it = _iter(rng)
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(10000)],
        iteration_termination_conditions=[
            MaxTimeIterationTerminationCondition(0.0)])
    result = EarlyStoppingTrainer(es, _net(), it).fit()
    assert result.termination_reason == "IterationTerminationCondition"


def test_invalid_score_termination(rng):
    it = _iter(rng)
    # absurd lr drives the score to nan quickly
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(500)],
        iteration_termination_conditions=[
            InvalidScoreIterationTerminationCondition()])
    net = _net(lr=1e12)
    result = EarlyStoppingTrainer(es, net, it).fit()
    assert result.termination_reason in ("IterationTerminationCondition",
                                         "EpochTerminationCondition")


def test_local_file_saver_restores_best(tmp_path, rng):
    it = _iter(rng)
    saver = LocalFileModelSaver(str(tmp_path))
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
        score_calculator=DataSetLossCalculator(_iter(rng)),
        model_saver=saver, save_last_model=True)
    result = EarlyStoppingTrainer(es, _net(), it).fit()
    best = saver.get_best_model()
    assert best is not None
    assert saver.get_latest_model() is not None
    x = np.asarray(rng.rand(4, 4), np.float32)
    assert np.asarray(best.output(x)).shape == (4, 3)
    assert result.best_model_score < float("inf")


def test_network_evaluate_roc_and_regression_methods():
    """evaluateROC / evaluateRegression / evaluateROCMultiClass parity
    (reference: MultiLayerNetwork.java:2422-2449, ComputationGraph
    analogs)."""
    import numpy as np
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.iterators import (BaseDatasetIterator)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    rng = np.random.default_rng(0)
    x = rng.standard_normal((80, 4)).astype(np.float32)
    cls = (x.sum(1) > 0).astype(int)
    y_bin = np.eye(2, dtype=np.float32)[cls]

    conf = (NeuralNetConfiguration(seed=1, updater="adam",
                                   learning_rate=0.05, activation="tanh")
            .list(DenseLayer(n_in=4, n_out=8),
                  OutputLayer(n_in=8, n_out=2, activation="softmax",
                              loss_function="mcxent")))
    net = MultiLayerNetwork(conf).init()
    it = BaseDatasetIterator(x, y_bin, batch_size=40)
    for _ in range(60):
        net.fit(it)
    roc = net.evaluate_roc(it)
    assert roc.calculate_auc() > 0.9
    rocm = net.evaluate_roc_multi_class(it)
    assert rocm.calculate_auc(0) > 0.9 and rocm.calculate_auc(1) > 0.9

    # regression head
    y_reg = (x.sum(1, keepdims=True) * 0.5).astype(np.float32)
    rconf = (NeuralNetConfiguration(seed=2, updater="adam",
                                    learning_rate=0.05, activation="tanh")
             .list(DenseLayer(n_in=4, n_out=8),
                   OutputLayer(n_in=8, n_out=1, activation="identity",
                               loss_function="mse")))
    rnet = MultiLayerNetwork(rconf).init()
    rit = BaseDatasetIterator(x, y_reg, batch_size=40)
    for _ in range(80):
        rnet.fit(rit)
    reg = rnet.evaluate_regression(rit)
    assert reg.pearson_correlation(0) > 0.8
    assert reg.average_mean_squared_error() < 0.5


def test_graph_evaluate_roc():
    import numpy as np
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.iterators import BaseDatasetIterator
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    rng = np.random.default_rng(3)
    x = rng.standard_normal((80, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    conf = (NeuralNetConfiguration(seed=1, updater="adam",
                                   learning_rate=0.05, activation="tanh")
            .graph_builder().add_inputs("in")
            .add_layer("h", DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=2,
                                          activation="softmax",
                                          loss_function="mcxent"), "h")
            .set_outputs("out").build())
    g = ComputationGraph(conf).init()
    it = BaseDatasetIterator(x, y, batch_size=40)
    for _ in range(60):
        g.fit(it)
    assert g.evaluate_roc(it).calculate_auc() > 0.9


def test_roc_auc_extreme_probabilities():
    """Regression: tied-FPR ordering must not collapse AUC to 0.5 for a
    perfectly separated classifier with saturated probabilities."""
    import numpy as np
    from deeplearning4j_tpu.eval.roc import ROC
    l = np.array([0] * 23 + [1] * 17)
    p = np.where(l == 1, 0.9999, 1e-5)
    r = ROC()
    r.eval(np.eye(2)[l], np.stack([1 - p, p], 1))
    assert r.calculate_auc() > 0.99
    # and an anti-classifier scores near 0
    r2 = ROC()
    r2.eval(np.eye(2)[l], np.stack([p, 1 - p], 1))
    assert r2.calculate_auc() < 0.1
