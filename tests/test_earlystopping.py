"""Early stopping tests (reference test analog:
deeplearning4j-core/src/test/java/org/deeplearning4j/earlystopping/
TestEarlyStopping.java)."""
import numpy as np

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.iterators import (BaseDatasetIterator,
                                                   DataSet)
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InMemoryModelSaver, InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer


def _net(lr=0.05):
    conf = (NeuralNetConfiguration(seed=1, updater="adam", learning_rate=lr)
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax",
                              loss_function="mcxent")))
    return MultiLayerNetwork(conf).init()


def _iter(rng, n=60, batch=20):
    x = rng.rand(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return BaseDatasetIterator(x, y, batch_size=batch)


def test_max_epochs_termination(rng):
    it = _iter(rng)
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
        score_calculator=DataSetLossCalculator(_iter(rng)),
        model_saver=InMemoryModelSaver())
    result = EarlyStoppingTrainer(es, _net(), it).fit()
    assert result.termination_reason == "EpochTerminationCondition"
    assert result.total_epochs == 5
    assert result.best_model is not None
    assert len(result.score_vs_epoch) == 5


def test_score_improvement_patience(rng):
    it = _iter(rng)
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(3),
            MaxEpochsTerminationCondition(200)],
        score_calculator=DataSetLossCalculator(_iter(rng)))
    # lr=0 -> no improvement ever -> stops after patience epochs
    result = EarlyStoppingTrainer(es, _net(lr=0.0), it).fit()
    assert result.termination_reason == "EpochTerminationCondition"
    assert "ScoreImprovement" in result.termination_details
    assert result.total_epochs <= 5


def test_max_time_termination(rng):
    it = _iter(rng)
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(10000)],
        iteration_termination_conditions=[
            MaxTimeIterationTerminationCondition(0.0)])
    result = EarlyStoppingTrainer(es, _net(), it).fit()
    assert result.termination_reason == "IterationTerminationCondition"


def test_invalid_score_termination(rng):
    it = _iter(rng)
    # absurd lr drives the score to nan quickly
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(500)],
        iteration_termination_conditions=[
            InvalidScoreIterationTerminationCondition()])
    net = _net(lr=1e12)
    result = EarlyStoppingTrainer(es, net, it).fit()
    assert result.termination_reason in ("IterationTerminationCondition",
                                         "EpochTerminationCondition")


def test_local_file_saver_restores_best(tmp_path, rng):
    it = _iter(rng)
    saver = LocalFileModelSaver(str(tmp_path))
    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
        score_calculator=DataSetLossCalculator(_iter(rng)),
        model_saver=saver, save_last_model=True)
    result = EarlyStoppingTrainer(es, _net(), it).fit()
    best = saver.get_best_model()
    assert best is not None
    assert saver.get_latest_model() is not None
    x = np.asarray(rng.rand(4, 4), np.float32)
    assert np.asarray(best.output(x)).shape == (4, 3)
    assert result.best_model_score < float("inf")
