"""Native C++ IO library tests (native/dataloader.cpp via ctypes).

The library parallels the reference's native data path (DataVec loaders,
MnistDbFile IDX parsing, AsyncDataSetIterator prefetch — SURVEY.md §2.9);
tests verify parity between the native parsers and the pure-Python
fallbacks, and the threaded prefetcher's ordering.
"""
import gzip
import struct

import numpy as np
import pytest

from deeplearning4j_tpu import native_bridge as nb

pytestmark = pytest.mark.skipif(not nb.native_available(),
                                reason="native IO library unavailable")


def _write_idx(path, arr: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 8, arr.ndim))
        for s in arr.shape:
            f.write(struct.pack(">I", s))
        f.write(arr.tobytes())


def test_idx_native_matches_python(tmp_path):
    arr = np.arange(3 * 5 * 7, dtype=np.uint8).reshape(3, 5, 7)
    p = str(tmp_path / "t.idx")
    _write_idx(p, arr)
    got = nb.idx_read(p)
    np.testing.assert_array_equal(got, arr)
    from deeplearning4j_tpu.datasets.impl import _parse_idx
    np.testing.assert_array_equal(_parse_idx(open(p, "rb").read()), arr)


def test_idx_rejects_bad_magic(tmp_path):
    p = str(tmp_path / "bad.idx")
    open(p, "wb").write(b"\x01\x02\x03\x04garbage")
    assert nb.idx_read(p) is None


def test_csv_native_matches_python(tmp_path):
    p = str(tmp_path / "t.csv")
    open(p, "w").write("a,b,c\n1.5,2,3\n-4,5.25,6\n")
    mat = nb.csv_read_floats(p, skip_lines=1)
    np.testing.assert_allclose(mat, [[1.5, 2, 3], [-4, 5.25, 6]])


def test_cifar_native_parse(tmp_path):
    rng = np.random.default_rng(0)
    n = 4
    recs = b""
    labels = []
    pixels = []
    for i in range(n):
        lab = int(rng.integers(0, 10))
        px = rng.integers(0, 256, 3072).astype(np.uint8)  # CHW
        labels.append(lab)
        pixels.append(px)
        recs += bytes([lab]) + px.tobytes()
    p = str(tmp_path / "batch.bin")
    open(p, "wb").write(recs)
    imgs, labs = nb.cifar_read(p)
    assert imgs.shape == (n, 32, 32, 3)
    assert labs.tolist() == labels
    # pixel mapping: CHW/255 → HWC
    chw = pixels[0].reshape(3, 32, 32).astype(np.float32) / 255.0
    np.testing.assert_allclose(imgs[0], np.transpose(chw, (1, 2, 0)),
                               atol=1e-6)


def test_prefetcher_order_and_content(tmp_path):
    paths = []
    for i in range(5):
        p = tmp_path / f"f{i}.bin"
        p.write_bytes(bytes([i]) * (100 + i))
        paths.append(str(p))
    with nb.FilePrefetcher(paths, queue_cap=2) as pf:
        outs = list(pf)
    assert len(outs) == 5
    for i, o in enumerate(outs):
        assert len(o) == 100 + i and o[0] == i


def test_record_reader_native_path_matches_fallback(tmp_path):
    """The CSV fast path and the pure-Python path must produce identical
    DataSets."""
    from deeplearning4j_tpu.datasets.records import (
        CollectionRecordReader, CSVRecordReader, RecordReaderDataSetIterator)
    p = str(tmp_path / "d.csv")
    rows = [[1.0, 2.0, 0], [3.0, 4.0, 1], [5.0, 6.0, 2]]
    open(p, "w").write("\n".join(",".join(str(v) for v in r)
                                for r in rows) + "\n")
    fast = RecordReaderDataSetIterator(CSVRecordReader(p), 3, num_classes=3)
    slow = RecordReaderDataSetIterator(CollectionRecordReader(rows), 3,
                                       num_classes=3)
    bf, bs = next(iter(fast)), next(iter(slow))
    np.testing.assert_allclose(bf.features, bs.features)
    np.testing.assert_allclose(bf.labels, bs.labels)


def test_native_vocab_count_matches_python():
    from collections import Counter
    from deeplearning4j_tpu import native_bridge
    if not native_bridge.native_available():
        import pytest
        pytest.skip("native IO library unavailable")
    rng = __import__("random").Random(5)
    words = ["alpha", "beta", "Gamma", "delta-x", "e"]
    corpus = "\n".join(
        " ".join(rng.choice(words) for _ in range(rng.randint(1, 30)))
        for _ in range(500))
    got = native_bridge.vocab_count(corpus, lowercase=True, min_count=1)
    want = Counter(corpus.lower().split())
    assert got == dict(want)
    # min_count filters
    got2 = native_bridge.vocab_count(corpus, lowercase=False, min_count=2)
    want2 = {w: c for w, c in Counter(corpus.split()).items() if c >= 2}
    assert got2 == want2
    # multithreaded run is deterministic
    assert native_bridge.vocab_count(corpus, nthreads=7) \
        == native_bridge.vocab_count(corpus, nthreads=1)


def test_vocab_constructor_text_fast_path():
    from deeplearning4j_tpu.nlp.vocab import VocabConstructor
    text = "the cat sat\nthe cat ran\nthe dog sat\n"
    cache = VocabConstructor(min_word_frequency=2).build_vocab_from_text(
        text)
    words = set(cache.words())
    assert words == {"the", "cat", "sat"}
    assert cache.word_frequency("the") == 3.0


def test_native_window_pairs_matches_numpy():
    """C++ pair expansion == the numpy fallback bit-for-bit on the same
    (flat, sid, reduced-window) inputs — the r5 staging fast path's
    proof obligation."""
    from deeplearning4j_tpu import native_bridge
    if not native_bridge.native_available():
        pytest.skip("native IO library unavailable")
    rng = np.random.default_rng(0)
    n, window = 5000, 5
    flat = rng.integers(0, 200, n).astype(np.int32)
    lens = rng.integers(3, 40, 200)
    lens = lens[np.cumsum(lens) <= n]
    sid = np.repeat(np.arange(len(lens)), lens)
    sid = np.concatenate([sid, np.full(n - len(sid), len(lens))])
    sid = sid.astype(np.int32)
    w = (window - rng.integers(0, window, n)).astype(np.int32)
    native = native_bridge.window_pairs(flat, sid, w, window)
    assert native is not None
    # numpy fallback reimplemented exactly as in _corpus_window_pairs
    offs = np.concatenate([np.arange(-window, 0),
                           np.arange(1, window + 1)]).astype(np.int32)
    k = len(offs)
    ci = np.repeat(np.arange(n, dtype=np.int32), k)
    off_t = np.tile(offs, n)
    xi = ci + off_t
    valid = ((xi >= 0) & (xi < n)
             & (np.abs(off_t) <= np.repeat(w, k)))
    xi_c = np.clip(xi, 0, n - 1)
    valid &= sid[xi_c] == sid[ci]
    np.testing.assert_array_equal(native[0], flat[ci[valid]])
    np.testing.assert_array_equal(native[1], flat[xi[valid]])


def test_native_pair_shuffle_is_seeded_permutation():
    from deeplearning4j_tpu import native_bridge
    if not native_bridge.native_available():
        pytest.skip("native IO library unavailable")
    rng = np.random.default_rng(1)
    a = rng.integers(0, 1000, 4096).astype(np.int32)
    b = rng.integers(0, 1000, 4096).astype(np.int32)
    a1, b1 = a.copy(), b.copy()
    assert native_bridge.pair_shuffle(a1, b1, seed=42)
    # a permutation of the PAIRS (columns stay aligned)
    packed0 = sorted(zip(a.tolist(), b.tolist()))
    packed1 = sorted(zip(a1.tolist(), b1.tolist()))
    assert packed0 == packed1
    assert not np.array_equal(a1, a)
    # deterministic in the seed
    a2, b2 = a.copy(), b.copy()
    assert native_bridge.pair_shuffle(a2, b2, seed=42)
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(b1, b2)
    a3 = a.copy()
    assert native_bridge.pair_shuffle(a3, b.copy(), seed=43)
    assert not np.array_equal(a3, a1)


def test_native_neg_pool_fill_deterministic_and_in_range():
    from deeplearning4j_tpu import native_bridge
    if not native_bridge.native_available():
        pytest.skip("native IO library unavailable")
    table = np.arange(100, 400, dtype=np.int32)
    p1 = native_bridge.neg_pool_fill(table, (64, 32, 5), seed=7)
    p2 = native_bridge.neg_pool_fill(table, (64, 32, 5), seed=7)
    p3 = native_bridge.neg_pool_fill(table, (64, 32, 5), seed=8)
    assert p1 is not None and p1.shape == (64, 32, 5)
    np.testing.assert_array_equal(p1, p2)
    assert not np.array_equal(p1, p3)
    assert p1.min() >= 100 and p1.max() < 400
    # draws cover the table roughly uniformly
    counts = np.bincount(p1.ravel() - 100, minlength=300)
    assert counts.min() > 0
