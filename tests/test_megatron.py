"""Composite parallelism (TP/PP/SP/EP) equivalence tests on the virtual CPU
mesh — every strategy must reproduce single-device training numerically
(the framework's version of the reference's spark-vs-single-machine proof,
SURVEY.md §4)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params, loss_fn)
from deeplearning4j_tpu.parallel.megatron import (init_adam_state,
                                                  make_parallel_train_step,
                                                  shard_params)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.ring import ring_attention
from deeplearning4j_tpu.parallel.ulysses import ulysses_attention


CFG = TransformerConfig(vocab_size=50, d_model=32, n_heads=4, n_layers=4,
                        max_len=32)

# Parallel-vs-single param equality after 2 Adam steps. Reassociation
# noise in the gradients gets amplified by Adam's m/sqrt(v) at early
# steps, and the amplification is XLA-codegen dependent: 5e-4 covers
# every leaf on jax 0.8's CPU backend, while jax 0.4.x CPU fusion
# leaves ~1 element in 16k at 2-3.5e-3 (worst on the deep-pipeline
# meshes). The bound stays ~100x below the param scale, so the
# equivalence proof keeps its teeth; the loss checks stay at 1e-4.
ATOL_TRAIN = 5e-3


def _data(seed=0, b=8, t=32):
    rng = np.random.RandomState(seed)
    toks = jnp.asarray(rng.randint(0, 50, (b, t)).astype(np.int32))
    tgts = jnp.asarray(np.roll(np.asarray(toks), -1, 1).astype(np.int32))
    return toks, tgts


def _train(cfg, spec, toks, tgts, steps=2, lr=1e-2):
    mesh = make_mesh(spec)
    p = init_params(cfg, jax.random.PRNGKey(0))
    step = make_parallel_train_step(cfg, mesh, learning_rate=lr)
    ps = shard_params(p, cfg, mesh)
    st = init_adam_state(ps)
    for _ in range(steps):
        ps, st, loss = step(ps, st, toks, tgts)
    return jax.tree_util.tree_map(np.asarray, ps), float(loss)


@pytest.mark.parametrize("attn_fn", [ring_attention, ulysses_attention],
                         ids=["ring", "ulysses"])
def test_sequence_parallel_attention_matches_full(devices8, attn_fn):
    """Both SP strategies (ring K/V rotation, Ulysses all-to-all head
    resharding) == full single-device causal attention, fwd and grad."""
    from functools import partial

    try:
        from jax import shard_map
    except ImportError:      # jax<0.6: pre-promotion location
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
    rng = np.random.RandomState(0)
    q, k, v = (rng.randn(2, 32, 4, 8).astype(np.float32) for _ in range(3))
    ref = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), causal=True)
    # jax<0.7's legacy check_rep cannot track the transpose of the ring
    # scan (its own error message prescribes check_rep=False); the vma
    # system on newer jax handles it, so keep checking ON there
    import inspect
    compat = ({} if "check_vma" in inspect.signature(shard_map).parameters
              else {"check_rep": False})
    fn = jax.jit(shard_map(
        partial(attn_fn, axis_name="seq", causal=True), mesh=mesh,
        in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
        **compat))
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)
    # gradients flow through the collective identically
    gr = jax.grad(lambda a: jnp.sum(fn(a, k, v) ** 2))(jnp.asarray(q))
    gf = jax.grad(lambda a: jnp.sum(
        dot_product_attention(a, jnp.asarray(k), jnp.asarray(v),
                              causal=True) ** 2))(jnp.asarray(q))
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gf), atol=1e-5)


def test_ulysses_training_matches_single_device(devices8):
    """Composite step with seq_impl='ulysses' reproduces single-device
    training, including combined sp x tp (local heads 4/2=2, sp=2)."""
    toks, tgts = _data()
    base, base_loss = _train(CFG, MeshSpec(), toks, tgts)
    cfg_u = dataclasses.replace(CFG, seq_impl="ulysses")
    for spec in (MeshSpec(seq=2), MeshSpec(seq=2, model=2)):
        got, gl = _train(cfg_u, spec, toks, tgts)
        assert abs(gl - base_loss) < 1e-4
        for a, b in zip(jax.tree_util.tree_leaves(base),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_allclose(a, b, atol=ATOL_TRAIN)


@pytest.mark.parametrize("spec", [
    MeshSpec(model=2),
    MeshSpec(seq=2),
    MeshSpec(pipe=2),
    MeshSpec(pipe=2, data=2, model=2),
    MeshSpec(pipe=2, seq=2, model=2),
], ids=["tp", "sp", "pp", "pp-dp-tp", "pp-sp-tp"])
def test_parallel_training_matches_single_device(devices8, spec):
    toks, tgts = _data()
    base, base_loss = _train(CFG, MeshSpec(), toks, tgts)
    got, gl = _train(CFG, spec, toks, tgts)
    assert abs(gl - base_loss) < 1e-4
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(a, b, atol=ATOL_TRAIN)


def test_expert_parallel_matches_single_device(devices8):
    cfg = TransformerConfig(vocab_size=50, d_model=32, n_heads=4, n_layers=2,
                            max_len=32, n_experts=4, capacity_factor=8.0)
    toks, tgts = _data()
    base, base_loss = _train(cfg, MeshSpec(), toks, tgts)
    got, gl = _train(cfg, MeshSpec(data=4), toks, tgts)
    assert abs(gl - base_loss) < 1e-4
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(a, b, atol=8e-3)


def _train_sched(cfg, spec, toks, tgts, schedule, steps=2, lr=1e-2,
                 n_microbatches=None):
    mesh = make_mesh(spec)
    p = init_params(cfg, jax.random.PRNGKey(0))
    step = make_parallel_train_step(cfg, mesh, learning_rate=lr,
                                    pipeline_schedule=schedule,
                                    n_microbatches=n_microbatches)
    ps = shard_params(p, cfg, mesh)
    st = init_adam_state(ps)
    for _ in range(steps):
        ps, st, loss = step(ps, st, toks, tgts)
    return jax.tree_util.tree_map(np.asarray, ps), float(loss)


@pytest.mark.parametrize("spec,m", [
    (MeshSpec(pipe=2), None),
    (MeshSpec(pipe=4), None),
    (MeshSpec(pipe=2), 4),
    (MeshSpec(pipe=2, data=2, model=2), None),
], ids=["pp2", "pp4", "pp2-m4", "pp-dp-tp"])
def test_1f1b_matches_gpipe_and_single_device(devices8, spec, m):
    """The 1F1B schedule must be a pure re-scheduling: loss and every
    updated param leaf equal the GPipe path AND single-device training
    (same math, O(S) instead of O(M) activation store)."""
    toks, tgts = _data()
    base, base_loss = _train(CFG, MeshSpec(), toks, tgts)
    gp, gp_loss = _train_sched(CFG, spec, toks, tgts, "gpipe",
                               n_microbatches=m)
    fb, fb_loss = _train_sched(CFG, spec, toks, tgts, "1f1b",
                               n_microbatches=m)
    assert abs(fb_loss - base_loss) < 1e-4
    assert abs(fb_loss - gp_loss) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(fb)):
        np.testing.assert_allclose(a, b, atol=ATOL_TRAIN)
    # 1f1b sums grads per microbatch; gpipe's autodiff sums in a
    # different order — reassociation noise that Adam's m/sqrt(v)
    # amplifies at early steps, so same tolerance as vs single-device
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(fb)):
        np.testing.assert_allclose(a, b, atol=ATOL_TRAIN)


def test_1f1b_chunked_xent_and_remat(devices8):
    """1F1B composes with the streaming chunked cross-entropy head and
    with blockwise remat inside the stage function."""
    import dataclasses as dc
    cfg = dc.replace(CFG, xent_chunk=25, remat=True)
    toks, tgts = _data()
    base, base_loss = _train(cfg, MeshSpec(), toks, tgts)
    fb, fb_loss = _train_sched(cfg, MeshSpec(pipe=2, model=2), toks,
                               tgts, "1f1b", n_microbatches=4)
    assert abs(fb_loss - base_loss) < 1e-4
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(fb)):
        np.testing.assert_allclose(a, b, atol=ATOL_TRAIN)


def test_pipeline_bubble_fraction():
    from deeplearning4j_tpu.parallel.megatron import \
        pipeline_bubble_fraction
    assert pipeline_bubble_fraction("gpipe", 1, 8) == 0.0
    assert pipeline_bubble_fraction("gpipe", 4, 8) == pytest.approx(3 / 11)
    assert pipeline_bubble_fraction("1f1b", 4, 8) == pytest.approx(6 / 14)
    # the memory win converts to a bubble win at equal activation
    # budget: 1f1b at M=32 beats gpipe at M=8 (docstring rationale)
    assert (pipeline_bubble_fraction("1f1b", 4, 32)
            < pipeline_bubble_fraction("gpipe", 4, 8))
    with pytest.raises(ValueError, match="unknown"):
        pipeline_bubble_fraction("zb-h1", 4, 8)


def test_unknown_schedule_rejected(devices8):
    with pytest.raises(ValueError, match="pipeline_schedule"):
        make_parallel_train_step(CFG, make_mesh(MeshSpec(pipe=2)),
                                 pipeline_schedule="interleaved")


def test_parallel_loss_decreases(devices8):
    toks, tgts = _data()
    _, l0 = _train(CFG, MeshSpec(pipe=2, data=2, model=2), toks, tgts,
                   steps=1)
    _, l8 = _train(CFG, MeshSpec(pipe=2, data=2, model=2), toks, tgts,
                   steps=8)
    assert l8 < l0


def test_transformer_remat_same_loss_and_grads():
    """jax.checkpoint remat path is numerically identical to the
    standard path (memory-for-FLOPs only; net-new TPU capability,
    task-required long-context lever)."""
    from deeplearning4j_tpu.models.transformer import loss_fn

    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=3,
                max_len=32)
    cfg = TransformerConfig(**base)
    cfg_r = TransformerConfig(**base, remat=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    tgt = jnp.roll(tok, -1, axis=1)

    l1, g1 = jax.value_and_grad(lambda p: loss_fn(cfg, p, tok, tgt))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss_fn(cfg_r, p, tok, tgt))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_generate_top_k_and_top_p():
    """Sampling filters: top_k=1 == greedy; top-k/top-p draws stay
    inside the allowed candidate sets at every step; _filter_logits
    keeps exactly the documented tokens."""
    from deeplearning4j_tpu.models.transformer import (_filter_logits,
                                                       generate)
    cfg = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                            n_layers=2, max_len=48)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.zeros((2, 4), jnp.int32)
    key = jax.random.PRNGKey(3)
    greedy = np.asarray(generate(cfg, params, prompt, 12, key,
                                 temperature=0.0))
    k1 = np.asarray(generate(cfg, params, prompt, 12, key,
                             temperature=1.0, top_k=1))
    np.testing.assert_array_equal(k1, greedy)
    # k=5 actually samples (differs from k=1 for this seed — a top_k
    # no-op regression would fail this), deterministically per key
    k5a = np.asarray(generate(cfg, params, prompt, 12, key,
                              temperature=1.0, top_k=5))
    k5b = np.asarray(generate(cfg, params, prompt, 12, key,
                              temperature=1.0, top_k=5))
    np.testing.assert_array_equal(k5a, k5b)
    assert not np.array_equal(k5a, k1)
    # unfiltered sampling with the same key picks tokens OUTSIDE the
    # top-5 at some step; the filtered run must not equal it either
    free = np.asarray(generate(cfg, params, prompt, 12, key,
                               temperature=1.0))
    assert not np.array_equal(k5a, free)
    with pytest.raises(ValueError, match="top_p"):
        generate(cfg, params, prompt, 4, key, top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        generate(cfg, params, prompt, 4, key, top_k=-1)

    # unit checks on the filter itself
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]])
    f2 = np.asarray(_filter_logits(logits, 2, 1.0))[0]
    assert np.isinf(f2[:2]).all() and (f2[2:] == [2.0, 3.0]).all()
    # top_p tiny -> only the argmax survives
    fp = np.asarray(_filter_logits(logits, 0, 1e-6))[0]
    assert np.isfinite(fp[3]) and np.isinf(fp[:3]).all()
    # top_p that spans two tokens: softmax([0,1,2,3]) top probs are
    # ~0.644, ~0.237 -> cumulative 0.88; top_p=0.7 keeps both (the
    # mass reaches 0.7 only WITH the second token)
    fp2 = np.asarray(_filter_logits(logits, 0, 0.7))[0]
    assert np.isfinite(fp2[3]) and np.isfinite(fp2[2])
    assert np.isinf(fp2[:2]).all()


def test_parallel_training_chunked_xent_matches_single_device(devices8):
    """xent_chunk flows through the megatron sharded step: parallel
    training with the streaming vocab-panel loss == the dense-loss
    parallel path AND the single-device chunked loss_fn (the
    real-vocab flagship on a mesh)."""
    from deeplearning4j_tpu.models.transformer import loss_fn

    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=4,
                max_len=32)
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)
    tgts = jnp.roll(toks, -1, axis=1)
    spec = MeshSpec(data=2, model=2, seq=2)
    cfg_c = TransformerConfig(**base, xent_chunk=16)
    cfg_d = TransformerConfig(**base)
    got_c, loss_c = _train(cfg_c, spec, toks, tgts)
    got_d, loss_d = _train(cfg_d, spec, toks, tgts)
    np.testing.assert_allclose(loss_c, loss_d, rtol=1e-5)
    # params after TWO Adam steps: panel-order summation differs from
    # the dense reduction at f32 ulp level, and Adam's m/sqrt(v) near
    # init amplifies that to ~0.4% on individual weights — the loss
    # parity above and the lr=0 scalar check below are the tight
    # checks; this pins the updates to the same trajectory
    for a, b in zip(jax.tree_util.tree_leaves(got_c),
                    jax.tree_util.tree_leaves(got_d)):
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=1e-4)
    # scalar parity with the single-device chunked loss
    params = init_params(cfg_c, jax.random.PRNGKey(0))
    want = float(loss_fn(cfg_c, params, toks, tgts))
    _, l0 = _train(cfg_c, spec, toks, tgts, steps=1, lr=0.0)
    np.testing.assert_allclose(l0, want, rtol=1e-5)


def test_chunked_cross_entropy_matches_dense():
    """xent_chunk streaming loss == dense log_softmax loss in value AND
    grads (the real-vocab flagship path: never materializes [B,T,V])."""
    from deeplearning4j_tpu.models.transformer import (chunked_cross_entropy,
                                                       loss_fn)

    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                max_len=32)
    cfg_d = TransformerConfig(**base)
    cfg_c = TransformerConfig(**base, xent_chunk=16)
    params = init_params(cfg_d, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    tgt = jnp.roll(tok, -1, axis=1)
    l1, g1 = jax.value_and_grad(
        lambda p: loss_fn(cfg_d, p, tok, tgt))(params)
    l2, g2 = jax.value_and_grad(
        lambda p: loss_fn(cfg_c, p, tok, tgt))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # direct function check with adversarial logit magnitudes (the
    # online-logsumexp rescale must not overflow where a naive
    # sum-of-exp would)
    h = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 16)) * 30.0
    w = jax.random.normal(jax.random.PRNGKey(3), (16, 48))
    y = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0, 48)
    dense = -jnp.take_along_axis(
        jax.nn.log_softmax(jnp.matmul(h, w), axis=-1),
        y[..., None], axis=-1).mean()
    for c in (8, 16, 48):
        np.testing.assert_allclose(
            float(chunked_cross_entropy(h, w, y, c)), float(dense),
            rtol=1e-5)
    with pytest.raises(ValueError):
        chunked_cross_entropy(h, w, y, 13)


def test_kv_cache_decode_matches_full_forward():
    """Cached decode logits at each position == full-sequence forward
    logits (the correctness contract of the KV cache)."""
    from deeplearning4j_tpu.models.transformer import (decode_step,
                                                       forward,
                                                       init_cache)
    cfg = TransformerConfig(vocab_size=50, d_model=32, n_heads=4,
                            n_layers=2, max_len=16)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (3, 10), 0, 50)
    full = np.asarray(forward(cfg, params, tok))  # [3, 10, 50]

    caches = init_cache(cfg, 3)
    outs = []
    for t in range(10):
        logits, caches = decode_step(cfg, params, tok[:, t], caches,
                                     jnp.asarray(t, jnp.int32))
        outs.append(np.asarray(logits))
    np.testing.assert_allclose(np.stack(outs, 1), full, rtol=2e-4,
                               atol=2e-4)


def test_decode_step_branching_without_donation():
    """donate=False keeps the input caches valid — several continuations
    can branch from one prefill cache (the advisor's branching-decode
    scenario; the default donating path invalidates its input)."""
    from deeplearning4j_tpu.models.transformer import (decode_step,
                                                       init_cache, prefill)
    cfg = TransformerConfig(vocab_size=50, d_model=32, n_heads=4,
                            n_layers=2, max_len=16)
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 50)
    _, caches = prefill(cfg, params, prompt)
    pos = jnp.asarray(6, jnp.int32)
    tok_a = jnp.asarray([1, 2], jnp.int32)
    tok_b = jnp.asarray([3, 4], jnp.int32)
    la, _ = decode_step(cfg, params, tok_a, caches, pos, donate=False)
    # caches must still be alive and reusable for a second branch
    lb, _ = decode_step(cfg, params, tok_b, caches, pos, donate=False)
    assert np.isfinite(np.asarray(la)).all()
    assert np.isfinite(np.asarray(lb)).all()
    assert not np.allclose(np.asarray(la), np.asarray(lb))


def test_generate_greedy_and_sampled():
    from deeplearning4j_tpu.models.transformer import TransformerLM
    cfg = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                            n_layers=2, max_len=24)
    lm = TransformerLM(cfg, seed=3)
    prompt = np.array([[1, 2, 3], [4, 5, 6]], np.int32)
    out = np.asarray(lm.generate(prompt, 8, temperature=0.0))
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(out[:, :3], prompt)
    assert out.max() < 32 and out.min() >= 0
    # greedy is deterministic
    out2 = np.asarray(lm.generate(prompt, 8, temperature=0.0, seed=9))
    np.testing.assert_array_equal(out, out2)
    # sampling differs across seeds (overwhelmingly likely)
    s1 = np.asarray(lm.generate(prompt, 8, temperature=1.0, seed=0))
    s2 = np.asarray(lm.generate(prompt, 8, temperature=1.0, seed=1))
    assert not np.array_equal(s1, s2)
    # greedy continuation agrees with argmax over the full forward
    from deeplearning4j_tpu.models.transformer import forward
    ctx = out[:, :3]
    nxt = np.asarray(forward(cfg, lm.params, jnp.asarray(ctx)))[:, -1]
    np.testing.assert_array_equal(out[:, 3], nxt.argmax(-1))


def test_remat_policies_same_loss_and_grads():
    """remat off / 'full' / 'dots' / 'mlp' are pure memory-schedule
    choices — loss AND gradients must agree (round-3: the 'mlp' mode
    checkpoints only the MLP branch inside the scanned block)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       init_params,
                                                       loss_fn)

    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 64)),
                       jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)
    results = []
    for remat, pol in [(False, "full"), (True, "full"), (True, "dots"),
                       (True, "mlp")]:
        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=3, max_len=64, remat=remat,
                                remat_policy=pol)
        params = init_params(cfg, jax.random.PRNGKey(0))
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, toks, tgts))(params)
        results.append((float(loss), grads))
    base_loss, base_grads = results[0]
    for loss, grads in results[1:]:
        assert abs(loss - base_loss) < 1e-5, (loss, base_loss)
        for a, b in zip(jax.tree_util.tree_leaves(base_grads),
                        jax.tree_util.tree_leaves(grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5)
