"""Tenant QoS control plane (ISSUE-16).

The tentpole guarantees, each proven deterministically on the CPU
backend:

- weighted fair share: under sustained two-tenant contention the
  deficit scheduler converges the granted-prefill-token ratio to the
  configured weights, and a backlogged tenant behind a hostile flood
  reaches its first token within a bounded number of ticks
  (no-starvation) — where the QoS-off oldest-first scheduler provably
  starves it for the flood's whole prefill;
- priority preemption: a high-priority arrival with no free slot
  evicts the lowest-priority resident through the committed-prefix
  resume path (token-exact vs the uninterrupted reference), bounded
  by preemption_budget evictions per tick, and zero high-priority
  requests are lost under preemption + a replica kill;
- admission + overload control: per-tenant concurrency and rate caps
  reject at admission with the typed `TenantCapExceeded` (injected
  clock makes the token bucket deterministic), and the SLO-aware
  controller walks the degradation ladder spec-off -> chunk-shrink ->
  shed-lowest-priority and back down after the cooldown;
- legacy preservation: QoS-off engines produce bit-identical tokens
  with unchanged compile-cache keys and no qos metric series.
"""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.failure import (FleetFaultInjector,
                                                 hostile_tenant_storm,
                                                 storm_prompt)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (EngineConfig, FleetConfig,
                                        InferenceEngine, Router)
from deeplearning4j_tpu.serving.engine import (
    MAX_PRIORITY, QoSValidationError, _compiled_chunked_prefill,
    _compiled_decode_chunk, _compiled_prefill,
    validate_tenant_priority)
from deeplearning4j_tpu.serving.fleet import TenantCapExceeded
from helpers import assert_no_recompiles

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


def _config(**kw):
    base = dict(decode_chunk=2, max_new_tokens=4, backoff_base_s=0.0,
                prefill_chunk=4, max_batch_size=2)
    base.update(kw)
    return EngineConfig(**base)


def _solo(params, mesh, prompt, max_new):
    """Uninterrupted reference run — the token-exactness oracle."""
    eng = InferenceEngine(CFG, mesh, params,
                          _config(max_new_tokens=max_new))
    h = eng.submit(prompt, max_new_tokens=max_new)
    eng.run_pending()
    return h.result(0)


# ---------------------------------------------------------------------------
# submit() validation (satellite 1)
# ---------------------------------------------------------------------------

def test_validate_tenant_priority_coerce_or_reject():
    """The shared validator: int tenants coerce to their decimal
    string; everything else non-str — including bool — is rejected
    typed, as are exposition-breaking ids and out-of-range or
    non-int priorities."""
    assert validate_tenant_priority(None, 0) == (None, 0)
    assert validate_tenant_priority("acme", 3) == ("acme", 3)
    assert validate_tenant_priority(42, 0) == ("42", 0)
    for bad_tenant in ("", "a" * 65, 'evil"', "two\nlines",
                       "back\\slash", "bell\x07", 1.5, b"bytes",
                       True, object()):
        with pytest.raises(QoSValidationError):
            validate_tenant_priority(bad_tenant, 0)
    for bad_prio in (-1, MAX_PRIORITY + 1, 1.0, "1", None, False):
        with pytest.raises(QoSValidationError):
            validate_tenant_priority("t", bad_prio)
    # the typed error IS a ValueError: pre-ISSUE-16 callers that
    # caught ValueError on submit keep working
    assert issubclass(QoSValidationError, ValueError)


def test_engine_and_router_submit_validate(params, mesh1):
    eng = InferenceEngine(CFG, mesh1, params, _config())
    with pytest.raises(QoSValidationError):
        eng.submit(_prompt(), tenant="bad\nid")
    with pytest.raises(QoSValidationError):
        eng.submit(_prompt(), priority=MAX_PRIORITY + 1)
    h = eng.submit(_prompt(), tenant=7, priority=2)
    assert (h.tenant, h.priority) == ("7", 2)
    eng.run_pending()
    assert h.error is None

    router = Router(cfg=CFG, mesh=mesh1, params=params,
                    num_replicas=1, engine_config=_config())
    try:
        with pytest.raises(QoSValidationError):
            router.submit(_prompt(), tenant="")
        with pytest.raises(QoSValidationError):
            router.submit(_prompt(), priority=-1)
        fr = router.submit(_prompt(), tenant=9, priority=1)
        assert (fr.tenant, fr.priority) == ("9", 1)
        router.run_pending()
        assert fr.error is None
    finally:
        router.close()


def test_qos_config_validation(params, mesh1):
    """Misconfigured QoS knobs fail at CONSTRUCTION, not mid-traffic."""
    with pytest.raises(ValueError):    # fair share needs the chunked
        InferenceEngine(CFG, mesh1, params,   # prefill scheduler
                        _config(prefill_chunk=None,
                                tenant_weights={"a": 1.0}))
    with pytest.raises(ValueError):
        InferenceEngine(CFG, mesh1, params,
                        _config(tenant_weights={"a": 0.0}))
    with pytest.raises(ValueError):
        InferenceEngine(CFG, mesh1, params,
                        _config(tenant_weights={"": 1.0}))
    with pytest.raises(ValueError):
        InferenceEngine(CFG, mesh1, params,
                        _config(preemption_budget=-1))
    with pytest.raises(ValueError):
        InferenceEngine(CFG, mesh1, params,
                        _config(mode="batch", decode_chunk=0,
                                prefill_chunk=None,
                                preemption_budget=1))


# ---------------------------------------------------------------------------
# weighted fair share (tentpole 1)
# ---------------------------------------------------------------------------

def test_weighted_share_ratio_converges(params, mesh1):
    """Two tenants, weights 3:1, both saturating the pool with long
    prompts under a small tick budget: the granted-prefill-token
    ratio converges to the weights (the serving_qos_prefill_tokens
    counters ARE the measurement)."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(max_batch_size=4, max_new_tokens=2,
                tick_token_budget=8,
                tenant_weights={"gold": 3.0, "bronze": 1.0}))
    for i in range(2):
        eng.submit(_prompt(48, i), tenant="gold")
        eng.submit(_prompt(48, 10 + i), tenant="bronze")
    for _ in range(8):
        eng.tick()
    gold = eng._m_qos_prefill_tokens.labels("gold").value
    bronze = eng._m_qos_prefill_tokens.labels("bronze").value
    assert gold > 0 and bronze > 0
    ratio = gold / bronze
    assert 2.0 <= ratio <= 4.0, \
        f"weighted share diverged from 3:1: {gold}/{bronze}={ratio}"
    # the deficit table only tracks live demand (both still backlogged)
    dz = eng.debugz()["qos"]
    assert set(dz["deficits"]) <= {"gold", "bronze"}
    eng.run_pending()   # everything still completes


def test_no_starvation_within_k_ticks(params, mesh1):
    """A small victim prompt co-resident with a hostile 48-token
    prefill reaches prefill-done within K ticks under fair share —
    while the QoS-off oldest-first scheduler provably serves the
    hostile prompt's ENTIRE prefill first."""
    def ticks_until_victim_decodes(weights):
        eng = InferenceEngine(
            CFG, mesh1, params,
            _config(max_new_tokens=2, tick_token_budget=4,
                    tenant_weights=weights))
        hostile = eng.submit(_prompt(48, 1), tenant="hostile")
        victim = eng.submit(_prompt(8, 2), tenant="victim")
        for t in range(1, 64):
            eng.tick()
            if victim._prefill_pos >= victim._prefill_target:
                eng.run_pending()
                assert victim.error is None and hostile.error is None
                return t
        pytest.fail("victim never finished prefill")

    fair = ticks_until_victim_decodes({"victim": 1.0, "hostile": 1.0})
    assert fair <= 8, f"victim starved {fair} ticks under fair share"
    fifo = ticks_until_victim_decodes(None)
    assert fifo >= 12, \
        f"control arm invalid: oldest-first served victim at {fifo}"


def test_idle_tenant_share_rolls_over(params, mesh1):
    """With only ONE tenant backlogged, fair share must not slow it
    down: the full budget lands on the backlogged tenant (idle keys
    are dropped, not banked) and throughput matches the QoS-off
    engine tick for tick."""
    def ticks_to_drain(weights):
        eng = InferenceEngine(
            CFG, mesh1, params,
            _config(max_new_tokens=2, tick_token_budget=8,
                    tenant_weights=weights))
        h = eng.submit(_prompt(48, 3), tenant="solo")
        for t in range(1, 64):
            eng.tick()
            if h.done():
                assert h.error is None
                return t
        pytest.fail("request never completed")

    assert ticks_to_drain({"solo": 1.0, "idle": 8.0}) \
        == ticks_to_drain(None)


# ---------------------------------------------------------------------------
# priority preemption (tentpole 2)
# ---------------------------------------------------------------------------

def test_priority_preempts_lowest_and_resumes_token_exact(params,
                                                          mesh1):
    """A priority-3 arrival with both slots held by priority-0
    decodes evicts exactly one victim (lowest class, youngest seat),
    seats immediately, and the victim resumes from its committed
    prefix to the SAME tokens as an uninterrupted run."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(max_new_tokens=8, preemption_budget=1))
    low = [eng.submit(_prompt(8, i), max_new_tokens=8, tenant="batch")
           for i in range(2)]
    eng.tick()                       # both seated, prefill advancing
    hi = eng.submit(_prompt(8, 5), max_new_tokens=8,
                    tenant="urgent", priority=3)
    eng.tick()                       # preempt + seat the class-3
    assert eng._m_qos_preemptions.labels("batch").value == 1
    evicted = [r for r in low
               if any(e.kind == "preempted"
                      and e.data.get("reason") == "priority"
                      for e in r.trace.events)]
    assert len(evicted) == 1
    ev = next(e for e in evicted[0].trace.events
              if e.kind == "preempted")
    assert ev.data["by"] == hi.rid
    eng.run_pending()
    for r in low + [hi]:
        assert r.error is None
        np.testing.assert_array_equal(
            r.result(0), _solo(params, mesh1, r.prompt, 8))


def test_preemption_budget_bounds_evictions_per_tick(params, mesh1):
    """Two waiting class-5 requests against a full pool of class-0
    residents: budget=1 evicts ONE resident per tick, not both at
    once — and nothing of any class is lost."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(max_new_tokens=8, preemption_budget=1))
    low = [eng.submit(_prompt(8, i), max_new_tokens=8)
           for i in range(2)]
    eng.tick()
    his = [eng.submit(_prompt(8, 7 + i), max_new_tokens=8, priority=5)
           for i in range(2)]
    eng.tick()
    assert eng._m_qos_preemptions.labels("default").value == 1
    eng.tick()
    assert eng._m_qos_preemptions.labels("default").value == 2
    eng.run_pending()
    for r in low + his:
        assert r.error is None
        np.testing.assert_array_equal(
            r.result(0), _solo(params, mesh1, r.prompt, 8))


def test_equal_priority_never_thrashes(params, mesh1):
    """A waiter only displaces a STRICTLY lower class: a storm of
    equal-priority arrivals degrades to ordinary queueing with zero
    preemptions."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(max_new_tokens=4, preemption_budget=4))
    hs = [eng.submit(_prompt(8, i), priority=3) for i in range(5)]
    eng.run_pending()
    assert all(h.error is None for h in hs)
    assert eng._m_qos_preemptions.labels("default").value == 0


def test_priority_overcommit_reaches_engine_preemption(params, mesh1):
    """A full fleet must not park a high class in the ROUTER queue
    where engine preemption cannot see it: priority_overcommit lets
    the dispatch over-commit one in-flight request so the engine
    evicts a class-0 resident for the seat. With overcommit 0 the
    same arrival waits its turn (zero preemptions, low done first)."""
    def run(overcommit):
        router = Router(
            cfg=CFG, mesh=mesh1, params=params, num_replicas=1,
            engine_config=_config(max_batch_size=1, max_new_tokens=8,
                                  preemption_budget=1),
            config=FleetConfig(priority_overcommit=overcommit))
        try:
            lo = router.submit(_prompt(8, 1), max_new_tokens=8,
                               priority=0)
            router.tick()            # lo dispatched + seated
            hi = router.submit(_prompt(8, 2), max_new_tokens=8,
                               priority=2)
            order = []
            for _ in range(400):
                router.tick()
                for name, h in (("lo", lo), ("hi", hi)):
                    if h.done() and name not in order:
                        order.append(name)
                if len(order) == 2:
                    break
            assert lo.error is None and hi.error is None
            eng = router._ctls[0].replica.engine
            pre = (eng._m_qos_preemptions.labels("default").value
                   if eng._m_qos_preemptions is not None else 0)
            return order, pre
        finally:
            router.close()

    order, pre = run(1)
    assert order == ["hi", "lo"] and pre == 1
    order, pre = run(0)
    assert order == ["lo", "hi"] and pre == 0


# ---------------------------------------------------------------------------
# hostile-tenant storm: fleet-level zero-lost-high-priority (+ kill)
# ---------------------------------------------------------------------------

def _run_storm(params, mesh1, arrivals, inj_kwargs):
    inj = FleetFaultInjector(**inj_kwargs)
    router = Router(
        cfg=CFG, mesh=mesh1, params=params, num_replicas=2,
        engine_config=_config(
            max_new_tokens=8, tick_token_budget=16,
            tenant_weights={"victim": 4.0},
            preemption_budget=1),
        fault_injector=inj,
        config=FleetConfig(restart_backoff_base_s=0.01))
    handles = {}
    try:
        pending = sorted(arrivals, key=lambda a: a.tick)
        tick = 0
        for _ in range(3000):
            while pending and pending[0].tick <= tick:
                a = pending.pop(0)
                handles[a] = router.submit(
                    storm_prompt(a, CFG.vocab_size),
                    max_new_tokens=min(a.max_new_tokens, 8),
                    tenant=a.tenant, priority=a.priority)
            router.tick()
            tick += 1
            if not pending and all(h.done()
                                   for h in handles.values()):
                break
        assert all(h.done() for h in handles.values())
    finally:
        router.close()
    return handles, inj


def test_storm_zero_lost_high_priority(params, mesh1):
    arrivals, ik = hostile_tenant_storm(
        ticks=10, hostiles=2, flood_per_tick=1, victim_every=2,
        victim_prompt=8, victim_new=8, hostile_prompt=24,
        hostile_new=8)
    assert ik == {}
    handles, _ = _run_storm(params, mesh1, arrivals, ik)
    victims = [(a, h) for a, h in handles.items()
               if a.tenant == "victim"]
    assert victims
    for a, h in victims:
        assert h.error is None, f"high-priority lost: {h.error}"
        assert h.generated.shape[0] == 8


def test_storm_zero_lost_high_priority_under_kill_one(params, mesh1):
    """Kill a replica mid-storm: failover + preemption together still
    lose ZERO high-priority requests (committed-prefix resume)."""
    arrivals, ik = hostile_tenant_storm(
        ticks=10, hostiles=2, flood_per_tick=1, victim_every=2,
        victim_prompt=8, victim_new=8, hostile_prompt=24,
        hostile_new=8, kill_tick=5, kill_replica=0)
    assert ik == {"kill_at": {5: 0}}
    handles, inj = _run_storm(params, mesh1, arrivals, ik)
    assert inj.kills_injected == 1
    for a, h in handles.items():
        if a.tenant != "victim":
            continue
        assert h.error is None, f"high-priority lost: {h.error}"
        assert h.generated.shape[0] == 8


def test_storm_generator_is_deterministic():
    a1, k1 = hostile_tenant_storm(ticks=40, kill_tick=7)
    a2, k2 = hostile_tenant_storm(ticks=40, kill_tick=7)
    assert a1 == a2 and k1 == k2
    p1 = storm_prompt(a1[3], CFG.vocab_size)
    p2 = storm_prompt(a2[3], CFG.vocab_size)
    np.testing.assert_array_equal(p1, p2)
    with pytest.raises(ValueError):
        hostile_tenant_storm(ticks=0)


# ---------------------------------------------------------------------------
# admission caps + SLO-aware overload control (tentpole 3)
# ---------------------------------------------------------------------------

def test_tenant_concurrency_cap_rejects_then_releases(params, mesh1):
    router = Router(
        cfg=CFG, mesh=mesh1, params=params, num_replicas=1,
        engine_config=_config(),
        config=FleetConfig(tenant_max_concurrency=2))
    try:
        hs = [router.submit(_prompt(8, i), tenant="capped")
              for i in range(2)]
        with pytest.raises(TenantCapExceeded):
            router.submit(_prompt(), tenant="capped")
        with pytest.raises(TenantCapExceeded):
            router.submit(_prompt(), tenant="capped")
        other = router.submit(_prompt(8, 4), tenant="other")
        router.run_pending()
        assert all(h.error is None for h in hs + [other])
        # terminal requests release their seats: same tenant admits
        again = router.submit(_prompt(8, 5), tenant="capped")
        router.run_pending()
        assert again.error is None
        assert router._m_qos_rejections.labels(
            "concurrency").value >= 2
        # TenantCapExceeded IS an OverloadError: pre-ISSUE-16 callers
        # treating rejections as overload keep working
        from deeplearning4j_tpu.serving.engine import OverloadError
        assert issubclass(TenantCapExceeded, OverloadError)
    finally:
        router.close()


def test_tenant_rate_cap_token_bucket_injected_clock(params, mesh1):
    class _Clk:
        t = 0.0

        def __call__(self):
            return self.t

    clk = _Clk()
    router = Router(
        cfg=CFG, mesh=mesh1, params=params, num_replicas=1,
        engine_config=_config(),
        config=FleetConfig(tenant_rate_per_s=1.0,
                           tenant_rate_burst=2),
        clock=clk)
    try:
        hs = [router.submit(_prompt(8, i), tenant="rl")
              for i in range(2)]           # burst of 2 admits
        with pytest.raises(TenantCapExceeded):
            router.submit(_prompt(), tenant="rl")
        assert router._m_qos_rejections.labels("rate").value == 1
        clk.t = 1.0                        # one token refilled
        hs.append(router.submit(_prompt(8, 3), tenant="rl"))
        with pytest.raises(TenantCapExceeded):
            router.submit(_prompt(), tenant="rl")
        # other tenants have their own buckets
        hs.append(router.submit(_prompt(8, 4), tenant="free"))
        router.run_pending()
        assert all(h.error is None for h in hs)
    finally:
        router.close()


def test_overload_ladder_degrades_and_restores(params, mesh1):
    """Deterministic queue-depth trigger: the controller walks
    spec-off -> chunk-shrink -> shed-lowest-priority one rung per
    check, the engine knobs actually move, rung 3 sheds the LOWEST
    class first (typed reason 'qos'), and the ladder unwinds after
    the cooldown once the queue drains."""
    router = Router(
        cfg=CFG, mesh=mesh1, params=params, num_replicas=1,
        engine_config=_config(max_batch_size=1, max_new_tokens=8),
        config=FleetConfig(overload_queue_depth=2,
                           overload_check_every_ticks=1,
                           overload_cooldown_ticks=3,
                           overload_shed_per_tick=2))
    try:
        eng = router._ctls[0].replica.engine
        base_chunk = eng._base_chunk
        keep = [router.submit(_prompt(8, i), priority=2)
                for i in range(2)]
        flood = [router.submit(_prompt(8, 10 + i))
                 for i in range(8)]
        for _ in range(3):
            router.tick()
        dz = router.debugz()["qos"]
        assert dz["level"] == 3
        assert eng._qos_spec_off is True
        assert eng._chunk == max(1, base_chunk // 2)
        shed = [h for h in flood if h.done() and h.error is not None]
        assert shed, "rung 3 shed nothing"
        assert router._m_shed_qos.value >= len(shed)
        # the class-2 requests were NOT shed (lowest-priority-first)
        assert not any(h.done() and h.error is not None
                       for h in keep)
        router.run_pending()               # drain the survivors
        for h in keep:
            assert h.error is None
        for _ in range(16):                # healthy ticks: unwind
            router.tick()
        dz = router.debugz()["qos"]
        assert dz["level"] == 0
        assert eng._qos_spec_off is False
        assert eng._chunk == base_chunk
        acts = router._m_qos_actions
        assert acts.labels("degrade_spec_off").value == 1
        assert acts.labels("degrade_chunk_shrink").value == 1
        assert acts.labels("degrade_shed_low").value == 1
        assert acts.labels("restore_none").value == 1
        # every transition is a typed qos trace event
        kinds = [(e.data.get("action"), e.data.get("step"))
                 for e in router.recorder.recent(200)
                 if e.kind == "qos"]
        assert ("degrade", "spec_off") in kinds
        assert ("restore", "none") in kinds
    finally:
        router.close()


# ---------------------------------------------------------------------------
# debugz surfaces (satellite 2)
# ---------------------------------------------------------------------------

def test_debugz_tenant_priority_columns(params, mesh1):
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(max_batch_size=1, preemption_budget=1,
                tick_token_budget=8,
                tenant_weights={"a": 2.0}))
    eng.submit(_prompt(8, 0), tenant="a", priority=1)
    eng.submit(_prompt(8, 1), tenant="b")
    eng.submit(_prompt(8, 2), tenant="b")
    eng.tick()
    d = eng.debugz()
    assert all({"tenant", "priority"} <= set(row)
               for row in d["slots"] + d["queue"])
    assert d["queue_by_tenant"] == {"b": 2}
    assert d["qos"]["preemption_budget"] == 1
    assert d["qos"]["tenant_weights"] == {"a": 2.0}
    eng.run_pending()

    router = Router(cfg=CFG, mesh=mesh1, params=params,
                    num_replicas=1,
                    engine_config=_config(max_batch_size=1),
                    config=FleetConfig(tenant_max_concurrency=8))
    try:
        for i in range(3):
            router.submit(_prompt(8, i), tenant="x", priority=i % 2)
        d = router.debugz()
        assert all({"tenant", "priority"} <= set(row)
                   for row in d["queue"])
        assert d["queue_by_tenant"].get("x", 0) >= 1
        assert d["qos"]["tenant_max_concurrency"] == 8
        assert "tenant_live" in d["qos"]
        router.run_pending()
    finally:
        router.close()


# ---------------------------------------------------------------------------
# legacy preservation: QoS off is bit-identical, same compile keys
# ---------------------------------------------------------------------------

def test_qos_off_bit_identical_no_new_compile_keys(params, mesh1):
    """A QoS-off engine built after the baseline reuses every compiled
    program (zero new cache entries — the cache keys did not move)
    and produces byte-identical tokens; a QoS-ON engine changes
    scheduling only, so its tokens match too."""
    ref = _solo(params, mesh1, _prompt(24, 6), 4)
    with assert_no_recompiles(_compiled_prefill,
                              _compiled_chunked_prefill,
                              _compiled_decode_chunk):
        eng = InferenceEngine(CFG, mesh1, params, _config())
        h = eng.submit(_prompt(24, 6))
        eng.run_pending()
    np.testing.assert_array_equal(h.result(0), ref)

    qos = InferenceEngine(
        CFG, mesh1, params,
        _config(tick_token_budget=8, preemption_budget=1,
                tenant_weights={"gold": 3.0}))
    hq = qos.submit(_prompt(24, 6), tenant="gold", priority=1)
    qos.run_pending()
    np.testing.assert_array_equal(hq.result(0), ref)


def test_qos_off_engine_has_no_qos_series(params, mesh1):
    from deeplearning4j_tpu.observability.export import prometheus_text
    eng = InferenceEngine(CFG, mesh1, params, _config())
    h = eng.submit(_prompt(), tenant="t")
    eng.run_pending()
    assert h.error is None
    assert "qos" not in prometheus_text(eng.registry)
