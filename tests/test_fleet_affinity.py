"""Fleet-wide prefix-cache affinity dispatch + KV migration (ISSUE-14).

The properties, each proven deterministically on CPU:

- digest mechanics: chain hashes are deterministic and page-aligned,
  the top-K ranking advertises the hottest/deepest chains, the bloom
  false-positive rate respects its analytic bound, the generation
  counter bumps on insert/evict/flush (the idle-replica staleness
  fix), and the digest is stable (cached) across probe cycles;
- affinity dispatch: two requests sharing a system prompt land on the
  SAME replica (counted serving_fleet_affinity_hits_total), the
  anti-herd cap spills a hot tenant off an occupied replica, and a
  stale advertisement ages out by TTL;
- KV migration: capacity-forced spillover ships the cached chain to
  the cold replica (engine.export_cached_chain -> cache-source
  KVHandoff -> radix-cache seed), which then serves the request as an
  ordinary prefix hit — token-exact, no re-prefill of the shared
  chain, and zero steady-state recompiles on the adopt path;
- mispredicts (evicted chain / bloom false positive) cost one normal
  prefill and are counted, never wrong.
"""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (EngineConfig, FleetConfig,
                                        InferenceEngine, Router)
from deeplearning4j_tpu.serving.engine import (_compiled_chain_adopt,
                                               _compiled_page_gather)
from deeplearning4j_tpu.serving.paging import (PageAllocator,
                                               RadixPrefixCache,
                                               chain_hashes,
                                               digest_lookup)
from helpers import assert_no_recompiles

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)
PS = 4                                     # page size under test
SHARED = np.arange(16, dtype=np.int32)     # 4 full pages


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(i):
    """SHARED system prompt + a 2-token per-request tail."""
    return np.concatenate([SHARED,
                           np.asarray([5 + i, (7 + i) % 32], np.int32)])


def _config(**kw):
    base = dict(decode_chunk=2, max_new_tokens=4, max_batch_size=1,
                num_slots=1, paged=True, page_size=PS,
                backoff_base_s=0.0)
    base.update(kw)
    return EngineConfig(**base)


def _router(params, mesh1, n=2, fleet_kw=None, **cfg_kw):
    return Router(cfg=CFG, mesh=mesh1, params=params, num_replicas=n,
                  engine_config=_config(**cfg_kw),
                  config=FleetConfig(migrate_min_tokens=8,
                                     **(fleet_kw or {})))


def _dispatch_replicas(fr):
    return [e.data["replica"] for e in fr.trace.events
            if e.kind == "dispatched"]


# ---------------------------------------------------------------------------
# digest mechanics
# ---------------------------------------------------------------------------

def test_chain_hashes_deterministic_and_page_aligned():
    toks = np.arange(19, dtype=np.int32)   # 4 full pages + 3 tail
    hs = chain_hashes(toks, PS)
    assert len(hs) == 4                    # the tail never hashes
    assert hs == chain_hashes(toks.tolist(), PS)
    # prefix property: shorter prompts share the leading hashes
    assert chain_hashes(toks[:8], PS) == hs[:2]
    # content-sensitive
    other = toks.copy()
    other[0] += 1
    assert chain_hashes(other, PS)[0] != hs[0]


def _warm_cache(chains):
    """A radix cache whose sole owner is the cache itself (the
    post-_free_slot steady state)."""
    al = PageAllocator(256, PS)
    c = RadixPrefixCache(PS, al)
    for toks in chains:
        pages = [al.alloc() for _ in range(len(toks) // PS)]
        c.insert(toks, pages)
        al.release_chain(pages)
    return c, al


def test_digest_top_k_ranks_hot_chains_and_matches_exactly():
    chains = [np.arange(100 * i, 100 * i + 16, dtype=np.int32) % 97
              for i in range(6)]
    c, _ = _warm_cache(chains)
    # touch chain 3 last: its nodes are the most recent
    c.match(chains[3])
    d = c.chain_digest(top_k=4)
    assert d["entries"] == 24 and d["page_size"] == PS
    assert len(d["top"]) == 4
    top_hashes = {h for h, _ in d["top"]}
    want = chain_hashes(chains[3], PS)
    assert want[-1] in top_hashes          # the hot deep chain leads
    # exact lookup on the hot chain, bloom fallback on a cold one
    toks, h = digest_lookup(d, want)
    assert toks == 16 and h == want[-1]
    toks0, _ = digest_lookup(d, chain_hashes(chains[0], PS))
    assert toks0 == 16                     # via bloom


def test_digest_bloom_false_positive_bound():
    """Measured per-hash FP rate over absent probes stays within 2x
    the analytic (1 - e^{-kn/m})^k bound (+ small-sample slack)."""
    import math
    from deeplearning4j_tpu.serving.paging import bloom_has
    chains = [np.arange(31 * i, 31 * i + 16, dtype=np.int32) % 1009
              for i in range(16)]
    c, _ = _warm_cache(chains)
    d = c.chain_digest(top_k=0)            # bloom-only digest
    n = d["entries"]
    m, k = d["bloom_m"], d["bloom_k"]
    bits = int(d["bloom"], 16)
    bound = (1 - math.exp(-k * n / m)) ** k
    trials, fp = 5000, 0
    rng = np.random.default_rng(7)
    for h in rng.integers(1, 2 ** 63, trials):
        fp += bloom_has(bits, int(h), m, k)
    rate = fp / trials
    assert rate <= 2 * bound + 0.01, \
        f"bloom FP {rate:.4f} vs bound {bound:.4f} (n={n})"


def test_generation_bumps_on_insert_evict_flush():
    c, al = _warm_cache([np.arange(16, dtype=np.int32)])
    g0 = c.generation
    assert g0 >= 1
    assert c.evict(1) == 1
    assert c.generation == g0 + 1
    pages = [al.alloc() for _ in range(2)]
    c.insert(np.arange(50, 58, dtype=np.int32), pages)
    al.release_chain(pages)
    assert c.generation == g0 + 2
    c.flush()
    assert c.generation == g0 + 3
    # and the digest is REBUILT per generation, cached within one
    d = c.chain_digest()
    assert d["generation"] == c.generation
    assert c.chain_digest() is d


def test_digest_stable_across_probe_cycles(params, mesh1):
    """An idle engine's health probes return the SAME digest object
    cycle after cycle (generation-keyed cache) — and traffic moves
    the generation."""
    eng = InferenceEngine(CFG, mesh1, params, _config())
    h = eng.submit(_prompt(0))
    eng.run_pending()
    d1 = eng.health()["prefix_digest"]
    d2 = eng.health()["prefix_digest"]
    assert d1 is d2                        # cached: idle probes are free
    g = d1["generation"]
    h2 = eng.submit(np.arange(40, 58, dtype=np.int32) % 32)
    eng.run_pending()
    assert eng.health()["prefix_digest"]["generation"] > g
    assert h.done() and h2.done()


# ---------------------------------------------------------------------------
# affinity dispatch
# ---------------------------------------------------------------------------

def test_shared_prompt_lands_on_the_same_replica(params, mesh1):
    """The e2e affinity property: with equal occupancy everywhere, a
    request sharing an already-served system prompt follows the cache
    — counted as an affinity hit, served as a prefix-cache hit."""
    router = _router(params, mesh1)
    try:
        h0 = router.submit(_prompt(0))
        router.run_pending()
        first = _dispatch_replicas(h0)[0]
        h1 = router.submit(_prompt(1))
        router.run_pending()
        ev = [e for e in h1.trace.events if e.kind == "dispatched"][0]
        assert ev.data["replica"] == first
        assert ev.data["affinity_tokens"] >= SHARED.shape[0]
        assert router.stats["affinity_hits"] == 1
        assert router.stats["affinity_mispredicts"] == 0
        eng = router._ctl(first).replica.engine
        assert eng.registry.get(
            "serving_prefix_cache_hits").value == 1
    finally:
        router.close()


def test_occupancy_only_control_arm_ignores_affinity(params, mesh1):
    """affinity_weight=0 is the bench's control: dispatch falls back
    to pure occupancy and no affinity series moves."""
    router = _router(params, mesh1,
                     fleet_kw=dict(affinity_weight=0.0,
                                   migrate_kv=False))
    try:
        for i in range(3):
            router.submit(_prompt(i))
            router.run_pending()
        assert router.stats["affinity_hits"] == 0
        assert router.stats["kv_migrations_ok"] == 0
    finally:
        router.close()


def test_anti_herd_cap_spills_to_an_emptier_replica(params, mesh1):
    """A warm replica at/above the occupancy cap gets NO affinity
    bonus: the shared-prefix request spills to the empty replica
    instead of piling onto the hot one (which, with seats still free,
    plain affinity WOULD have picked)."""
    router = _router(params, mesh1,
                     fleet_kw=dict(migrate_kv=False,
                                   affinity_max_occupancy=0.5),
                     max_new_tokens=24, decode_chunk=2,
                     num_slots=2, max_batch_size=2)
    try:
        h0 = router.submit(_prompt(0))
        router.run_pending()
        first = _dispatch_replicas(h0)[0]
        # park a long decode on the warm replica (affinity sends it
        # there; occupancy then sits AT the 0.5 cap), then submit a
        # shared-prefix request while it is still resident
        long = router.submit(_prompt(1), max_new_tokens=24)
        for _ in range(200):
            router.tick()
            if _dispatch_replicas(long):
                break
        assert _dispatch_replicas(long) == [first]
        h2 = router.submit(_prompt(2))
        router.run_pending()
        # a free seat remained on the warm replica — only the
        # anti-herd cap explains the spill
        assert _dispatch_replicas(h2)[0] == 1 - first
        assert long.done() and h2.done()
    finally:
        router.close()


def test_stale_digest_ages_out_by_ttl(params, mesh1):
    """An advertisement older than affinity_digest_ttl_s is ignored —
    probes that stopped refreshing a digest stop attracting traffic."""
    router = _router(params, mesh1)
    try:
        h0 = router.submit(_prompt(0))
        router.run_pending()
        ctl = router._ctl(_dispatch_replicas(h0)[0])
        assert ctl.digest is not None
        now = router._clock()
        assert router._affinity_tokens(ctl, _FR(_prompt(1)), now)[0] \
            >= SHARED.shape[0]
        ctl.digest_at = now - (router.config.affinity_digest_ttl_s + 1)
        assert router._affinity_tokens(ctl, _FR(_prompt(1)),
                                       now) == (0, None)
    finally:
        router.close()


class _FR:
    """Minimal FleetHandle stand-in for the affinity-lookup unit."""

    def __init__(self, prompt):
        self.prompt = np.asarray(prompt, np.int32)
        self._chain_hashes = {}


# ---------------------------------------------------------------------------
# KV migration
# ---------------------------------------------------------------------------

def test_migration_seeds_the_cold_replica(params, mesh1):
    """THE scale-out property: capacity forces a shared-prefix request
    onto the cold replica; the router ships the chain with the
    dispatch; the cold replica serves it as an ordinary prefix hit —
    no re-prefill of the shared chain, token-exact vs a solo run."""
    router = _router(params, mesh1)
    try:
        h0 = router.submit(_prompt(0))
        router.run_pending()
        first = _dispatch_replicas(h0)[0]
        # two CONCURRENT shared-prefix requests against capacity-1
        # replicas: one must spill to the cold replica
        ha = router.submit(_prompt(1))
        hb = router.submit(_prompt(2))
        router.run_pending()
        s = router.stats
        assert s["kv_migrations_ok"] == 1, s
        assert s["kv_migrated_tokens"] >= SHARED.shape[0]
        spilled = [fr for fr in (ha, hb)
                   if _dispatch_replicas(fr)[0] != first]
        assert len(spilled) == 1
        mig = [e for fr in (ha, hb) for e in fr.trace.events
               if e.kind == "kv_migration"]
        assert len(mig) == 1 and mig[0].data["outcome"] == "ok"
        assert mig[0].data["from"] == first
        assert mig[0].data["tokens"] >= SHARED.shape[0]
        cold = router._ctl(1 - first).replica.engine
        assert cold.registry.get(
            "serving_prefix_cache_hits").value >= 1
        assert cold.registry.get(
            "serving_prefix_shared_tokens").value >= SHARED.shape[0]
        # the cold replica prefilled ONLY the private tail
        assert cold.registry.get("serving_prefill_tokens").value \
            <= _prompt(1).shape[0] - SHARED.shape[0] + PS
        # token-exact vs solo runs
        for fr in (ha, hb):
            solo = InferenceEngine(CFG, mesh1, params, _config())
            hs = solo.submit(fr.prompt)
            solo.run_pending()
            np.testing.assert_array_equal(
                np.concatenate([fr.prompt, fr.generated]),
                hs.result(0))
        # debugz surfaces the advertisement
        rows = router.debugz()["replicas"]
        assert all(r["prefix_digest"] is not None for r in rows)
    finally:
        router.close()


def test_migration_adopt_path_never_recompiles(params, mesh1):
    """helpers.assert_no_recompiles over the migration adopt path
    (ISSUE-14 satellite): after the first migration warms the
    chain-adopt/page-gather programs, further migrations of OTHER
    tenants compile nothing — chains, pages, and indices are all
    runtime data."""
    router = _router(params, mesh1)
    try:
        def tenant_wave(base):
            shared = (np.arange(16, dtype=np.int32) + base) % 29
            h0 = router.submit(np.concatenate(
                [shared, np.asarray([1 + base % 7, 2], np.int32)]))
            router.run_pending()
            ha = router.submit(np.concatenate(
                [shared, np.asarray([3, 4 + base % 5], np.int32)]))
            hb = router.submit(np.concatenate(
                [shared, np.asarray([5, 6], np.int32)]))
            router.run_pending()
            assert h0.done() and ha.done() and hb.done()

        tenant_wave(0)                     # warms the adopt programs
        before = router.stats["kv_migrations_ok"]
        assert before >= 1
        with assert_no_recompiles(_compiled_chain_adopt,
                                  _compiled_page_gather):
            tenant_wave(100)
        assert router.stats["kv_migrations_ok"] > before
    finally:
        router.close()


def test_stale_advertised_chain_counts_stale_and_mispredict(params,
                                                            mesh1):
    """A digest advertising a chain the source has since evicted:
    export returns None (stale), the request prefills normally on its
    target, and the mispredict counter catches the shortfall. Probes
    are slowed to one (tick 0) so the pinned stale advertisement is
    exactly what a router between probe cycles would hold."""
    router = _router(params, mesh1,
                     fleet_kw=dict(probe_every_ticks=10 ** 6))
    try:
        h0 = router.submit(_prompt(0))
        router.run_pending()
        first = _dispatch_replicas(h0)[0]
        warm_eng = router._ctl(first).replica.engine
        stale_digest = warm_eng.health()["prefix_digest"]
        assert stale_digest["entries"] > 0
        # flush the source cache behind the advertisement's back and
        # pin the stale digest on the warm replica only: the first
        # concurrent request follows the (stale) affinity there and
        # MISPREDICTS; the second spills to the cold replica, whose
        # migration pull finds the chain gone — STALE
        warm_eng._prefix_cache.flush()
        now = router._clock()
        for ctl in router._ctls:
            ctl.digest = (dict(stale_digest) if ctl.id == first
                          else None)
            ctl.digest_at = now
        ha = router.submit(_prompt(1))
        hb = router.submit(_prompt(2))
        router.run_pending()
        s = router.stats
        assert s["kv_migrations_stale"] >= 1, s
        assert s["affinity_mispredicts"] >= 1, s
        for fr in (ha, hb):
            assert fr.status == "completed"
    finally:
        router.close()


def test_cache_source_handoff_weights_skew_refused(params, mesh1):
    """A migrated chain encodes the exporter's weights: a target on a
    different weights version refuses the seed (counted seed_failed)
    and prefills — correct tokens, no poisoned cache."""
    src = InferenceEngine(CFG, mesh1, params, _config())
    h = src.submit(_prompt(0))
    src.run_pending()
    dg = src.health()["prefix_digest"]
    toks, ch = digest_lookup(dg, chain_hashes(_prompt(1), PS))
    kvh = src.export_cached_chain(ch)
    assert kvh is not None and kvh.weights_step is None
    kvh.weights_step = 41                  # simulate exporter skew
    dst = InferenceEngine(CFG, mesh1, params, _config())
    h2 = dst.submit(_prompt(1), kv=kvh)
    dst.run_pending()
    solo = InferenceEngine(CFG, mesh1, params, _config())
    hs = solo.submit(_prompt(1))
    solo.run_pending()
    np.testing.assert_array_equal(h2.result(0), hs.result(0))
    assert len(dst._prefix_cache._by_hash) > 0  # its OWN insert only
    fam = dst.registry.get("serving_kv_adoptions")
    vals = {labels[0]: child.value for labels, child in fam.collect()}
    assert vals.get("seed_failed", 0) == 1
    assert h.done()


# ---------------------------------------------------------------------------
# cross-host compile-cache priming (ISSUE-14 satellite)
# ---------------------------------------------------------------------------

def test_autoscaled_fresh_replica_inherits_compile_cache(
        tmp_path, params, mesh1):
    """A tier config carrying compile_cache_dir reaches autoscale-
    built FRESH replicas (the scale-onto-new-host priming path), and
    the warm/cold verdict surfaces per replica."""
    from deeplearning4j_tpu.serving import AutoscalePolicy, TieredRouter
    from deeplearning4j_tpu.serving.disagg import PREFILL
    from deeplearning4j_tpu.serving.fleet import _warmup_cache_warm
    cache_dir = str(tmp_path / "aot")
    ec = _config(compile_cache_dir=cache_dir)
    router = TieredRouter(cfg=CFG, mesh=mesh1, params=params,
                          prefill_replicas=1, decode_replicas=1,
                          prefill_engine_config=ec,
                          decode_engine_config=ec,
                          prefill_autoscale=AutoscalePolicy(
                              min_replicas=1, max_replicas=2))
    try:
        assert router._scale_up(PREFILL, router._clock())
        fresh = router._tier_ctls(PREFILL)[-1]
        eng = fresh.replica.engine
        assert eng.config.compile_cache_dir == cache_dir
        from deeplearning4j_tpu.serving.compile_cache import \
            CompileCache
        if CompileCache.available():
            assert eng._aot is not None
        # warm-vs-cold classification from warmup reports
        assert _warmup_cache_warm(None) is None
        assert _warmup_cache_warm({"jit": 0, "aot_cache": 5}) is True
        assert _warmup_cache_warm({"jit": 3, "aot_cache": 0}) is False
        rows = router.debugz()["replicas"]
        assert all("cache_warm" in r for r in rows)
    finally:
        router.close()
