"""FSDP/ZeRO-style fully-sharded data parallelism (parallel/fsdp.py).

The reference's data-parallel modes replicate the full model per worker
(ParallelWrapper.java:603; Spark broadcast) — sharded-state DP is
net-new. Proof obligations: (1) numerics identical to single-device
training, (2) per-device param/opt-state memory actually drops by the
axis size for shardable leaves.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.fsdp import (fsdp_leaf_spec,
                                              init_fsdp_adam_state,
                                              make_fsdp_train_step,
                                              shard_params_fsdp)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh

CFG = TransformerConfig(vocab_size=50, d_model=32, n_heads=4, n_layers=4,
                        max_len=32)


def test_fsdp_leaf_spec_rules():
    # largest divisible axis is sharded
    assert fsdp_leaf_spec((4, 32, 64), 8) == P(None, None, "data")
    # largest axis not divisible -> next largest divisible one
    assert fsdp_leaf_spec((50, 32), 8) == P(None, "data")
    # nothing divisible -> replicated
    assert fsdp_leaf_spec((7, 3), 8) == P()
    assert fsdp_leaf_spec((), 8) == P()
    # axis of exactly the mesh size is eligible
    assert fsdp_leaf_spec((8,), 8) == P("data")
    # size-1 axis (no mesh) -> replicated
    assert fsdp_leaf_spec((64, 64), 1) == P()


def _data(seed=0, b=8, t=32):
    rng = np.random.RandomState(seed)
    toks = jnp.asarray(rng.randint(0, 50, (b, t)).astype(np.int32))
    tgts = jnp.asarray(np.roll(np.asarray(toks), -1, 1).astype(np.int32))
    return toks, tgts


def _train(mesh_spec, steps=3):
    mesh = make_mesh(mesh_spec)
    params = shard_params_fsdp(init_params(CFG, jax.random.PRNGKey(0)),
                               mesh)
    opt = init_fsdp_adam_state(params)
    step = make_fsdp_train_step(CFG, mesh, learning_rate=1e-2)
    toks, tgts = _data()
    for _ in range(steps):
        params, opt, loss = step(params, opt, toks, tgts)
    return params, opt, float(loss)


def test_fsdp_matches_single_device(devices8):
    base_p, _, base_loss = _train(MeshSpec())
    got_p, _, got_loss = _train(MeshSpec(data=8))
    assert abs(got_loss - base_loss) < 1e-4
    for a, b in zip(jax.tree_util.tree_leaves(base_p),
                    jax.tree_util.tree_leaves(got_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_fsdp_state_is_actually_sharded(devices8):
    mesh = make_mesh(MeshSpec(data=8))
    params = shard_params_fsdp(init_params(CFG, jax.random.PRNGKey(0)),
                               mesh)
    opt = init_fsdp_adam_state(params)
    wq = params["blocks"]["Wq"]          # [L=4, 32, 32]: d axis sharded
    assert wq.sharding.spec != P()
    local = wq.addressable_shards[0].data
    assert local.size == wq.size // 8
    # optimizer state inherits the shards (ZeRO-1 half of the win)
    mu_wq = opt.m["blocks"]["Wq"]
    assert mu_wq.addressable_shards[0].data.size == mu_wq.size // 8
    # embed's vocab axis (50) is indivisible; its d axis shards instead
    emb = params["embed"]                # [50, 32] -> d axis sharded
    assert emb.addressable_shards[0].data.size == emb.size // 8


def test_fsdp_replicated_leaves_stay_whole(devices8):
    """Leaves with no axis divisible by the mesh (odd-shaped norms/
    biases) are replicated intact — every device holds the full leaf."""
    mesh = make_mesh(MeshSpec(data=8))
    tree = {"w": jnp.ones((16, 64)), "odd": jnp.ones((7, 3)),
            "scalar": jnp.ones(())}
    placed = shard_params_fsdp(tree, mesh)
    assert placed["w"].addressable_shards[0].data.size == 16 * 64 // 8
    for name in ("odd", "scalar"):
        leaf = placed[name]
        assert leaf.sharding.spec == P()
        assert leaf.addressable_shards[0].data.size == leaf.size
        np.testing.assert_array_equal(
            np.asarray(leaf.addressable_shards[0].data),
            np.asarray(tree[name]))


@pytest.mark.parametrize("use_orbax", [True, False], ids=["orbax", "npz"])
def test_fsdp_checkpoint_resume(devices8, tmp_path, use_orbax):
    """Distributed checkpoint/resume of a sharded training state
    (SURVEY §5.3/5.4 TPU-native answer): save mid-run, restore into
    freshly-placed shards via a sharded template, and continue — must
    equal the uninterrupted run, with shards preserved."""
    from deeplearning4j_tpu.util.checkpointing import (CheckpointManager,
                                                       HAVE_ORBAX)
    if use_orbax and not HAVE_ORBAX:
        pytest.skip("orbax unavailable")
    mesh = make_mesh(MeshSpec(data=8))
    toks, tgts = _data()

    def fresh():
        p = shard_params_fsdp(init_params(CFG, jax.random.PRNGKey(0)), mesh)
        return p, init_fsdp_adam_state(p)

    step = make_fsdp_train_step(CFG, mesh, learning_rate=1e-2)
    # uninterrupted 4 steps
    p_ref, o_ref = fresh()
    for _ in range(4):
        p_ref, o_ref, _ = step(p_ref, o_ref, toks, tgts)

    # 2 steps -> save -> restore into a fresh sharded template -> 2 more
    p, o = fresh()
    for _ in range(2):
        p, o, _ = step(p, o, toks, tgts)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), use_orbax=use_orbax)
    mgr.save_tree({"params": p, "opt": o}, step=2)
    tmpl_p, tmpl_o = fresh()
    restored = mgr.restore_tree({"params": tmpl_p, "opt": tmpl_o})
    p2, o2 = restored["params"], restored["opt"]
    # shardings survive the round-trip
    wq = p2["blocks"]["Wq"]
    assert wq.addressable_shards[0].data.size == wq.size // 8
    for _ in range(2):
        p2, o2, _ = step(p2, o2, toks, tgts)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


def test_restore_tree_abstract_template_npz(devices8, tmp_path):
    """npz-fallback restore with a jax.eval_shape abstract template
    (ShapeDtypeStructs carrying .sharding) re-places leaves onto their
    shards — same contract the orbax path honors (advisor r1 finding:
    abstract templates silently yielded unsharded host arrays)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.util.checkpointing import CheckpointManager

    mesh = make_mesh(MeshSpec(data=8))
    sharding = NamedSharding(mesh, P("data"))
    x = jax.device_put(jnp.arange(16, dtype=jnp.float32), sharding)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), use_orbax=False)
    mgr.save_tree({"x": x}, step=1)

    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=a.sharding), {"x": x})
    restored = mgr.restore_tree(abstract)["x"]
    assert restored.sharding == sharding
    assert restored.addressable_shards[0].data.size == restored.size // 8
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(x))


def test_fsdp_loss_decreases(devices8):
    _, _, l3 = _train(MeshSpec(data=8), steps=1)
    _, _, l8 = _train(MeshSpec(data=8), steps=10)
    assert l8 < l3
