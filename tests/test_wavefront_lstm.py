"""Cross-layer LSTM wavefront fusion == the sequential per-layer scans
(nn/layers/recurrent.wavefront_scan_stack; measured 1.14-1.28x on chip,
benchmarks/lstm_stack_experiment.py). Exactness is the scan-everything
house rule's proof obligation: same cell math, same states, same final
carries, through the full MultiLayerNetwork surface."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (GravesLSTM,
                                          GravesBidirectionalLSTM,
                                          RnnOutputLayer)
from deeplearning4j_tpu.nn.layers.recurrent import (
    wavefront_eligible_run, wavefront_scan_stack)


def _mk_net(seed=3, layers=2, dropout=0.0):
    ls = [GravesLSTM(n_in=5 if i == 0 else 12, n_out=12,
                     activation="tanh",
                     dropout=dropout if i > 0 else 0.0)
          for i in range(layers)]
    conf = (NeuralNetConfiguration(seed=seed, updater="sgd",
                                   learning_rate=0.1)
            .list(*ls, RnnOutputLayer(n_in=12, n_out=4,
                                      activation="softmax",
                                      loss_function="mcxent")))
    return MultiLayerNetwork(conf).init()


def test_stack_matches_sequential_scans_and_carries():
    """Direct check at n=3 (deeper than the benchmarked pair):
    outputs AND per-layer final carries equal the chained
    scan_sequence path."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 9, 5)), jnp.float32)
    layers = [GravesLSTM(n_in=5, n_out=8, activation="tanh"),
              GravesLSTM(n_in=8, n_out=8, activation="tanh"),
              GravesLSTM(n_in=8, n_out=8, activation="tanh")]
    plist = [l.init_params(jax.random.PRNGKey(i)) for i, l in
             enumerate(layers)]
    ys, finals = wavefront_scan_stack(layers, plist, x)
    h = x
    for l, p, fc in zip(layers, plist, finals):
        h, carry = l.scan_sequence(p, h)
        np.testing.assert_allclose(np.asarray(carry[0]),
                                   np.asarray(fc[0]), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(carry[1]),
                                   np.asarray(fc[1]), rtol=1e-5,
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(h),
                               rtol=1e-5, atol=1e-6)


def test_mln_output_and_training_match_with_fusion_off(monkeypatch):
    """The full MLN surface: inference output and one fit_batched
    epoch (i.e. gradients) are equal with the wavefront disabled vs
    enabled."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 7, 5)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (8, 7))]

    monkeypatch.setenv("DL4JTPU_WAVEFRONT", "0")
    net_off = _mk_net()
    out_off = np.asarray(net_off.output(x))
    s_off = np.asarray(net_off.fit_batched(x[None], y[None], epochs=3))
    p_off = jax.tree_util.tree_leaves(net_off.params)

    monkeypatch.delenv("DL4JTPU_WAVEFRONT")
    net_on = _mk_net()
    out_on = np.asarray(net_on.output(x))
    s_on = np.asarray(net_on.fit_batched(x[None], y[None], epochs=3))
    p_on = jax.tree_util.tree_leaves(net_on.params)

    np.testing.assert_allclose(out_off, out_on, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(s_off, s_on, rtol=1e-5, atol=1e-6)
    for a, b in zip(p_off, p_on):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_tbptt_carry_path_matches(monkeypatch):
    """TBPTT streams (h, c) carries between chunks through the fused
    path — scores must match the unfused run chunk for chunk."""
    from deeplearning4j_tpu.models.zoo import char_rnn_lstm
    rng = np.random.default_rng(2)
    V, B, T = 11, 4, 24
    ids = rng.integers(0, V, (B, T))
    x = np.eye(V, dtype=np.float32)[ids]
    y = np.eye(V, dtype=np.float32)[np.roll(ids, -1, axis=1)]

    def run():
        conf = char_rnn_lstm(vocab_size=V, hidden=10, layers=2,
                             tbptt_length=8, dtype="float32")
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y)
        return (float(net.score_value),
                jax.tree_util.tree_leaves(net.params))

    monkeypatch.setenv("DL4JTPU_WAVEFRONT", "0")
    s_off, p_off = run()
    monkeypatch.delenv("DL4JTPU_WAVEFRONT")
    s_on, p_on = run()
    np.testing.assert_allclose(s_off, s_on, rtol=1e-5)
    for a, b in zip(p_off, p_on):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_eligibility_rules():
    l1 = GravesLSTM(n_in=5, n_out=8)
    l2 = GravesLSTM(n_in=8, n_out=8)
    bi = GravesBidirectionalLSTM(n_in=8, n_out=8)
    names = ["a", "b", "c"]
    assert wavefront_eligible_run(
        [l1, l2, bi], names, 0, train=False, mask=None, carries=None,
        preprocessors={}) == [0, 1]
    # bidirectional breaks the run; a run of one is no run
    assert wavefront_eligible_run(
        [l1, bi, l2], names, 0, train=False, mask=None, carries=None,
        preprocessors={}) == []
    # mask disables
    assert wavefront_eligible_run(
        [l1, l2], names[:2], 0, train=False, mask=jnp.ones((2, 4)),
        carries=None, preprocessors={}) == []
    # train-time dropout on the SECOND layer breaks fusion
    l2d = GravesLSTM(n_in=8, n_out=8, dropout=0.5)
    assert wavefront_eligible_run(
        [l1, l2d], names[:2], 0, train=True, mask=None, carries=None,
        preprocessors={}) == []
    assert wavefront_eligible_run(
        [l1, l2d], names[:2], 0, train=False, mask=None, carries=None,
        preprocessors={}) == [0, 1]
    # partial carries coverage disables (all-or-nothing)
    assert wavefront_eligible_run(
        [l1, l2], names[:2], 0, train=False, mask=None,
        carries={"a": 1}, preprocessors={}) == []
    assert wavefront_eligible_run(
        [l1, l2], names[:2], 0, train=False, mask=None,
        carries={"a": 1, "b": 2}, preprocessors={}) == [0, 1]
