"""Driver-contract regression tests for __graft_entry__.py.

Round-1 postmortem: MULTICHIP_r01.json recorded ok=false because
dryrun_multichip assumed the caller had already provisioned a virtual
CPU mesh (tests/conftest.py does; the driver does not — it invokes the
entry point under the default axon environment where a sitecustomize
has bound jax to the single TPU chip). dryrun_multichip must therefore
self-bootstrap. These tests run it in a fresh subprocess that inherits
the ambient environment — the closest in-suite reproduction of the
driver's invocation.
"""
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_dryrun_multichip_subprocess_ambient_env():
    """dryrun_multichip(8) must succeed from a fresh interpreter with NO
    conftest bootstrap — exactly how the driver calls it. conftest mutates
    XLA_FLAGS in this process; strip it so the child sees the driver's
    ambient environment (where XLA_FLAGS is unset)."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    # all four composite-parallel configs must report OK
    assert proc.stdout.count("OK") >= 4, proc.stdout


def test_force_virtual_cpu_mesh_idempotent_on_cpu():
    """Under the test env (8 CPU devices already live) the bootstrap must
    be a no-op — no backend reset, same client before and after."""
    import jax

    from __graft_entry__ import _force_virtual_cpu_mesh

    before = jax.devices()
    _force_virtual_cpu_mesh(8)
    after = jax.devices()
    assert before == after and len(after) >= 8
