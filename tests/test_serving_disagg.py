"""Disaggregated prefill/decode tiers: deterministic CPU suite.

Every ISSUE-11 acceptance behavior:

- the cross-tier KV handoff is TOKEN-EXACT: a request prefilled on
  tier P and decoded on tier D produces bit-identical tokens to a
  single-replica run — greedy and sampled, float and int8 KV, fresh
  and prefix-hit, one-shot and chunked prefill tiers;
- `PageAllocator`-backed export/adopt round-trips the committed rows
  (and quantized per-row scales) bit-exactly, adopting into a
  near-full pool BLOCKS-or-sheds instead of corrupting residents, and
  every adoption error path decrefs what it claimed (the
  `_free_slot`-style refcount audit) with the typed
  ``shed{reason="handoff"}``;
- a killed decode replica's requests generalize round-14 failover by
  RE-PREFILLING on the prefill tier (hitting its prefix cache), then
  handing off again — zero lost requests;
- a failed KV export degrades to re-prefill on the decode tier
  (``outcome="failed"``), never a lost request;
- the occupancy-driven `Autoscaler` scales each tier independently
  between min/max replicas through drain + supervised-restart
  machinery — an up/down cycle loses zero requests, and the prefill
  tier scales to ZERO under decode-only idle and force-scales back up
  on the next admission.
"""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.parallel.failure import (FleetFaultInjector,
                                                 ServingFaultInjector)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.quant.kv import handoff_bytes
from deeplearning4j_tpu.serving import (AutoscalePolicy, Autoscaler,
                                        EngineConfig, FleetConfig,
                                        HandoffError, InferenceEngine,
                                        RequestStatus, TieredRouter)

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


def _ec(**kw):
    base = dict(decode_chunk=2, max_new_tokens=12, backoff_base_s=0.0,
                max_batch_size=2, paged=True)
    base.update(kw)
    return EngineConfig(**base)


def _tiered(params, mesh, *, prefill=1, decode=1, pc=None, dc=None,
            **kw):
    return TieredRouter(cfg=CFG, mesh=mesh, params=params,
                        prefill_replicas=prefill,
                        decode_replicas=decode,
                        prefill_engine_config=pc or _ec(),
                        decode_engine_config=dc or _ec(),
                        config=kw.pop("config", FleetConfig(
                            restart_backoff_base_s=0.01)), **kw)


def _reference(params, mesh, prompts, max_new=12, ec=None):
    """Uninterrupted single-engine run — the token-exactness oracle."""
    eng = InferenceEngine(CFG, mesh, params, ec or _ec())
    out = []
    for p in prompts:
        h = eng.submit(p, max_new_tokens=max_new)
        eng.run_pending()
        out.append(h.result(0))
    return out


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _drive(router, clock=None, step=0.05, limit=3000):
    """Bounded run-to-completion, advancing an injected clock if any."""
    for _ in range(limit):
        if not router.pending():
            return
        router.tick()
        if clock is not None:
            clock.advance(step)
    raise AssertionError("tiered router failed to drain within bound")


# ---------------------------------------------------------------------------
# token-exact handoff
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_quantize,temperature", [
    (None, 0.0),             # float KV, greedy
    ("int8", 0.0),           # quantized KV: rows + scales travel
    (None, 0.8),             # sampled: position-keyed schedule
], ids=["float-greedy", "int8-greedy", "float-sampled"])
def test_handoff_token_exact(params, mesh1, kv_quantize, temperature):
    """Prefill on tier P + decode on tier D == one replica, bit for
    bit — the acceptance bar. Every request takes the full two-hop
    path (handoffs == completions, outcome ok)."""
    ec = _ec(kv_quantize=kv_quantize, temperature=temperature)
    prompts = [_prompt(6 + i, i) for i in range(5)]
    want = _reference(params, mesh1, prompts, ec=ec)
    r = _tiered(params, mesh1, pc=ec, dc=ec)
    try:
        hs = [r.submit(p, max_new_tokens=12) for p in prompts]
        _drive(r)
        for h, w in zip(hs, want):
            np.testing.assert_array_equal(h.result(0), w)
            assert h.status == RequestStatus.COMPLETED
        assert r.stats["completed"] == 5
        assert r.stats["handoffs_ok"] == 5
        assert r.stats["handoffs_failed"] == 0
    finally:
        r.close()


def test_handoff_prefix_hit_token_exact(params, mesh1):
    """A second tenant sharing the first's prompt hits the PREFILL
    tier's radix cache (prefill resumes from the hit boundary), and
    the handed-off continuation is still bit-exact."""
    shared = _prompt(32, 3)
    prompts = [shared, shared.copy()]
    want = _reference(params, mesh1, prompts)
    r = _tiered(params, mesh1)
    try:
        hs = []
        for p in prompts:       # serialize so the 2nd sees the cache
            hs.append(r.submit(p, max_new_tokens=12))
            _drive(r)
        for h, w in zip(hs, want):
            np.testing.assert_array_equal(h.result(0), w)
        pre_eng = r._ctls[0].replica.engine
        assert int(pre_eng._m_prefix_hits.value) >= 1
    finally:
        r.close()


def test_chunked_prefill_tier_token_exact(params, mesh1):
    """The prefill tier runs the round-15 chunked scheduler; the
    decode tier never prefills — still bit-exact vs a single chunked
    engine."""
    pc = _ec(prefill_chunk=8)
    prompts = [_prompt(20, i) for i in range(3)]
    want = _reference(params, mesh1, prompts, ec=pc)
    r = _tiered(params, mesh1, pc=pc)
    try:
        hs = [r.submit(p, max_new_tokens=12) for p in prompts]
        _drive(r)
        for h, w in zip(hs, want):
            np.testing.assert_array_equal(h.result(0), w)
        assert r.stats["handoffs_ok"] == 3
    finally:
        r.close()


def test_trace_carries_handoff_event(params, mesh1):
    r = _tiered(params, mesh1)
    try:
        h = r.submit(_prompt(), max_new_tokens=8)
        _drive(r)
        kinds = h.trace.kinds()
        assert "handoff" in kinds
        ev = next(e for e in h.trace.events if e.kind == "handoff")
        assert ev.data["outcome"] == "ok"
        assert ev.data["tokens"] >= 8      # the committed prefix rows
        # two dispatches bracket the handoff: prefill hop, decode hop
        assert kinds.count("dispatched") == 2
        assert kinds.index("dispatched") < kinds.index("handoff")
    finally:
        r.close()


# ---------------------------------------------------------------------------
# export / adopt mechanics (engine level)
# ---------------------------------------------------------------------------

def _held_export(params, mesh, ec, prompt, release=True):
    """Prefill `prompt` on a fresh engine with hold_kv and export."""
    eng = InferenceEngine(CFG, mesh, params, ec)
    h = eng.submit(prompt, max_new_tokens=1, hold_kv=True)
    eng.run_pending()
    assert h.done()
    kv = eng.export_slot_kv(h, release=release)
    return eng, h, kv


@pytest.mark.parametrize("kv_quantize", [None, "int8"],
                         ids=["float", "int8"])
def test_export_adopt_roundtrip_bit_exact(params, mesh1, kv_quantize):
    """The committed rows (values AND per-row scales) survive
    host-gather -> device-put -> decode bit-exactly: re-exporting the
    adopting engine's pool returns the identical prefix — and the
    decode continuation equals the single-engine run."""
    ec = _ec(kv_quantize=kv_quantize)
    prompt = _prompt(10, 2)
    want = _reference(params, mesh1, [prompt], ec=ec)[0]
    src, h, kv = _held_export(params, mesh1, ec, prompt)
    assert kv.pos == prompt.shape[0]
    assert kv.tok == int(h.generated[-1])
    assert (kv.k_scale is not None) == (kv_quantize == "int8")
    dst = InferenceEngine(CFG, mesh1, params, ec)
    prompt_d = np.concatenate([prompt, h.generated]).astype(np.int32)
    hd = dst.submit(prompt_d, max_new_tokens=11, kv=kv, hold_kv=True)
    dst.run_pending()
    np.testing.assert_array_equal(hd.result(0), want)
    # the adopted prefix is still bit-identical in dst's pool
    back = dst.export_slot_kv(hd)
    np.testing.assert_array_equal(back.k[:, :kv.pos], kv.k)
    np.testing.assert_array_equal(back.v[:, :kv.pos], kv.v)
    if kv_quantize:
        np.testing.assert_array_equal(back.k_scale[:, :kv.pos],
                                      kv.k_scale)
        np.testing.assert_array_equal(back.v_scale[:, :kv.pos],
                                      kv.v_scale)
    assert int(dst._m_adoptions.labels("ok").value) == 1


def test_export_requires_hold_and_releases(params, mesh1):
    """Without hold_kv the slot reaps at completion (export raises);
    a held slot frees exactly once on export and occupancy returns to
    zero."""
    eng = InferenceEngine(CFG, mesh1, params, _ec())
    h = eng.submit(_prompt(), max_new_tokens=1)
    eng.run_pending()
    with pytest.raises(HandoffError, match="not resident"):
        eng.export_slot_kv(h)
    h2 = eng.submit(_prompt(9, 1), max_new_tokens=1, hold_kv=True)
    eng.run_pending()
    assert eng.committed_kv_pages(h2) >= 1
    assert not eng.drained()             # the hold keeps it seated
    eng.export_slot_kv(h2)               # release=True default
    assert eng.committed_kv_pages(h2) == 0
    assert eng.drained()
    assert eng.release_held(h2) is False  # idempotent


def test_handoff_bytes_match_analytic(params, mesh1):
    """Measured handoff payload == quant/kv.handoff_bytes — the
    accounting behind serving_handoff_bytes_total."""
    for kvq in (None, "int8"):
        _, _, kv = _held_export(params, mesh1, _ec(kv_quantize=kvq),
                                _prompt(12, 1))
        assert kv.nbytes == handoff_bytes(CFG, kv.pos, kv_mode=kvq,
                                          tp=1)


def test_adopt_near_full_pool_blocks_not_corrupts(params, mesh1):
    """Adoption into a pool too full to cover the chain BLOCKS at the
    queue head until a resident frees pages — the resident's tokens
    stay bit-exact (no write ever landed on its pages) and the
    adopted request then completes bit-exactly too."""
    ec = _ec(page_size=4, kv_pages=12, max_new_tokens=24,
             prefix_cache=False)
    res_prompt, ado_prompt = _prompt(8, 1), _prompt(8, 5)
    want_res = _reference(params, mesh1, [res_prompt], max_new=24,
                          ec=ec)[0]
    want_ado = _reference(params, mesh1, [ado_prompt], max_new=12,
                          ec=ec)[0]
    _, h_src, kv = _held_export(params, mesh1, ec, ado_prompt)
    dst = InferenceEngine(CFG, mesh1, params, ec)
    res = dst.submit(res_prompt, max_new_tokens=24)   # 8 pages
    dst.tick()                                        # resident seated
    prompt_d = np.concatenate([ado_prompt,
                               h_src.generated]).astype(np.int32)
    ado = dst.submit(prompt_d, max_new_tokens=11, kv=kv)  # needs 5
    dst.tick()
    assert not ado.done() and ado.status == RequestStatus.QUEUED
    assert int(dst._m_adoptions.labels("blocked").value) >= 1
    dst.run_pending()
    np.testing.assert_array_equal(res.result(0), want_res)
    np.testing.assert_array_equal(ado.result(0), want_ado)


def test_adopt_that_never_fits_is_rejected(params, mesh1):
    """A handoff no pool state could ever seat is rejected at
    submit() — typed ValueError, nothing allocated — the shed half of
    blocks-or-sheds (the block half: the near-full test above; the
    seat-time shed paths: the injector + misalignment tests below)."""
    ec = _ec(page_size=4, kv_pages=4, prefix_cache=False)
    _, h_src, kv = _held_export(params, mesh1, _ec(), _prompt(16, 2))
    dst = InferenceEngine(CFG, mesh1, params, ec)
    prompt_d = np.concatenate([_prompt(16, 2),
                               h_src.generated]).astype(np.int32)
    with pytest.raises(ValueError, match="could never be admitted"):
        dst.submit(prompt_d, max_new_tokens=1, kv=kv)
    assert dst._allocator.pages_used == 0


def test_adopt_fault_sheds_typed_and_decrefs(params, mesh1):
    """ServingFaultInjector.adopt_fail_requests: the decode-side
    adoption fails -> typed ``shed{reason="handoff"}``, HandoffError
    on the handle, reason="handoff" counter child, and EVERY page the
    adoption claimed decref'd (the refcount audit)."""
    _, h_src, kv = _held_export(params, mesh1, _ec(), _prompt(10, 4))
    inj = ServingFaultInjector(adopt_fail_requests=[1])
    dst = InferenceEngine(CFG, mesh1, params, _ec(),
                          fault_injector=inj)
    used0 = dst._allocator.pages_used if dst._paged else 0
    prompt_d = np.concatenate([_prompt(10, 4),
                               h_src.generated]).astype(np.int32)
    ado = dst.submit(prompt_d, max_new_tokens=11, kv=kv)
    dst.run_pending()
    assert inj.adoptions_failed == 1
    assert ado.status == RequestStatus.SHED
    assert isinstance(ado.error, HandoffError)
    ev = [e for e in ado.trace.events if e.kind == "shed"]
    assert ev and ev[0].data["reason"] == "handoff"
    assert dst._allocator.pages_used == used0
    assert int(dst._m_shed.labels("handoff").value) == 1
    assert int(dst._m_adoptions.labels("shed").value) == 1


def test_misaligned_handoff_sheds_typed(params, mesh1):
    """A handoff whose pending token disagrees with the committed
    prefix would decode silently wrong text — it must shed typed, not
    seat."""
    _, h_src, kv = _held_export(params, mesh1, _ec(), _prompt(10, 4))
    dst = InferenceEngine(CFG, mesh1, params, _ec())
    bad = np.concatenate([_prompt(10, 4),
                          [(int(h_src.generated[-1]) + 1)
                           % CFG.vocab_size]]).astype(np.int32)
    ado = dst.submit(bad, max_new_tokens=11, kv=kv)
    dst.run_pending()
    assert ado.status == RequestStatus.SHED
    assert isinstance(ado.error, HandoffError)
    assert dst._allocator.pages_used == 0


def test_unpaged_target_falls_back_to_prefill(params, mesh1):
    """An engine that cannot adopt (contiguous pool) drops the
    handoff with a warning and re-prefills — correct tokens, no shed."""
    ec = _ec(paged=False)
    _, h_src, kv = _held_export(params, mesh1, _ec(), _prompt(10, 1))
    want = _reference(params, mesh1, [_prompt(10, 1)], ec=_ec())[0]
    dst = InferenceEngine(CFG, mesh1, params, ec)
    prompt_d = np.concatenate([_prompt(10, 1),
                               h_src.generated]).astype(np.int32)
    ado = dst.submit(prompt_d, max_new_tokens=11, kv=kv)
    dst.run_pending()
    np.testing.assert_array_equal(ado.result(0), want)


# ---------------------------------------------------------------------------
# failover across the tier boundary
# ---------------------------------------------------------------------------

def test_kill_decode_replica_reprefills_on_prefill_tier(params, mesh1):
    """Round-14 failover generalized: a killed decode replica's
    requests reset to the PREFILL phase, re-prefill their committed
    prefix on the prefill tier, hand off again, and finish
    bit-identically to an uninterrupted run — zero lost requests."""
    prompts = [_prompt(8, i) for i in range(5)]
    want = _reference(params, mesh1, prompts)
    inj = FleetFaultInjector(kill_at={6: 1})   # replica 1 = decode
    r = _tiered(params, mesh1, decode=2, fault_injector=inj)
    try:
        hs = [r.submit(p, max_new_tokens=12) for p in prompts]
        _drive(r)
        for h, w in zip(hs, want):
            np.testing.assert_array_equal(h.result(0), w)
        assert inj.kills_injected == 1
        assert r.stats["failovers"] >= 1
        # the failovers re-prefilled AND re-handed-off
        assert r.stats["handoffs_ok"] > len(prompts)
        assert r.stats["shed_outage"] == 0
    finally:
        r.close()


def test_kill_prefill_replica_recovers(params, mesh1):
    """A killed prefill replica's in-flight prefills requeue (still
    phase prefill) and the supervised restart brings the tier back —
    zero lost, token-exact."""
    prompts = [_prompt(8, i) for i in range(4)]
    want = _reference(params, mesh1, prompts)
    inj = FleetFaultInjector(kill_at={1: 0})   # replica 0 = prefill
    r = _tiered(params, mesh1, decode=1, fault_injector=inj)
    try:
        hs = [r.submit(p, max_new_tokens=12) for p in prompts]
        _drive(r)
        for h, w in zip(hs, want):
            np.testing.assert_array_equal(h.result(0), w)
        assert inj.kills_injected == 1
        # the tier's ONLY prefill replica died with admissions still
        # queued: nothing can finish without the supervised restart
        assert r.stats["restarts"] >= 1
    finally:
        r.close()


def test_handoff_export_failure_falls_back(params, mesh1):
    """FleetFaultInjector.handoff_fail_at: the first export dies ->
    outcome "failed", the decode dispatch re-prefills the committed
    prefix, and the result is still bit-exact."""
    prompts = [_prompt(8, i) for i in range(3)]
    want = _reference(params, mesh1, prompts)
    inj = FleetFaultInjector(handoff_fail_at=[0])
    r = _tiered(params, mesh1, fault_injector=inj)
    try:
        hs = [r.submit(p, max_new_tokens=12) for p in prompts]
        _drive(r)
        for h, w in zip(hs, want):
            np.testing.assert_array_equal(h.result(0), w)
        assert inj.handoffs_failed == 1
        assert r.stats["handoffs_failed"] == 1
        assert r.stats["handoffs_ok"] == 2
        # the prefill tier's held slot was released despite the
        # injected failure (no leaked seats)
        assert r._ctls[0].replica.engine.drained()
    finally:
        r.close()


# ---------------------------------------------------------------------------
# autoscaling
# ---------------------------------------------------------------------------

def test_autoscaler_policy_unit():
    """The pure decision core: window hysteresis, cooldown, min/max
    bounds, scale-to-zero idle gate, and the cold-start force-up."""
    p = AutoscalePolicy(min_replicas=0, max_replicas=3, window=2,
                        cooldown_s=1.0, scale_up_occupancy=0.8,
                        scale_down_occupancy=0.2)
    a = Autoscaler(p)
    # one high observation is not enough (window=2)...
    assert a.observe(0.0, 1, 0.9, None, 2, 2) == 0
    assert a.observe(0.1, 1, 0.9, None, 2, 2) == 1
    # ...cooldown gates the next action...
    assert a.observe(0.2, 2, 0.9, None, 2, 2) == 0
    assert a.observe(0.3, 2, 0.9, None, 2, 2) == 0
    assert a.observe(1.2, 2, 0.9, None, 2, 2) == 1
    # ...max bound
    assert a.observe(3.0, 3, 1.0, None, 5, 5) == 0
    # idle: down after window, but the LAST replica only retires when
    # in-flight work is gone
    a2 = Autoscaler(p)
    assert a2.observe(0.0, 2, 0.0, None, 0, 0) == 0
    assert a2.observe(0.1, 2, 0.0, None, 0, 0) == -1
    a3 = Autoscaler(p)
    assert a3.observe(0.0, 1, 0.0, None, 0, 3) == 0
    assert a3.observe(0.1, 1, 0.0, None, 0, 3) == 0   # still serving
    assert a3.observe(1.2, 1, 0.0, None, 0, 0) == 0
    assert a3.observe(1.3, 1, 0.0, None, 0, 0) == -1  # to zero
    # cold start: pending work, zero active -> +1 immediately
    a4 = Autoscaler(p)
    assert a4.observe(0.0, 0, 0.0, None, 1, 0) == 1
    # budget utilization is an OR'd up-signal
    a5 = Autoscaler(p)
    assert a5.observe(0.0, 1, 0.1, 0.99, 1, 1) == 0
    assert a5.observe(0.1, 1, 0.1, 0.99, 1, 1) == 1
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalePolicy(min_replicas=3, max_replicas=1)


def test_autoscale_up_down_cycle_zero_lost(params, mesh1):
    """A burst scales the decode tier up (occupancy-driven), idleness
    scales it back to min through drain — zero lost requests, the
    trajectory lands in autoscale_log/metrics, and stopped replicas
    revive on the next burst."""
    clock = _Clock()
    r = _tiered(params, mesh1, decode=1,
                dc=_ec(max_new_tokens=16),
                pc=_ec(max_new_tokens=16),
                decode_autoscale=AutoscalePolicy(
                    min_replicas=1, max_replicas=3, window=2,
                    cooldown_s=0.1),
                clock=clock)
    try:
        hs = [r.submit(_prompt(8, i), max_new_tokens=16)
              for i in range(8)]
        _drive(r, clock)
        assert all(h.status == RequestStatus.COMPLETED for h in hs)
        ups = [e for e in r.autoscale_log
               if e["tier"] == "decode" and e["direction"] == "up"]
        assert ups, "the burst never scaled the decode tier up"
        for _ in range(60):               # idle: scale back down
            r.tick()
            clock.advance(0.05)
        downs = [e for e in r.autoscale_log
                 if e["tier"] == "decode" and e["direction"] == "down"]
        assert downs, "idleness never scaled the decode tier down"
        assert len(r._active_ctls("decode")) == 1
        stopped = [c for c in r._ctls if c.state() == "stopped"]
        assert stopped
        # second burst revives a stopped replica, still zero lost
        hs2 = [r.submit(_prompt(8, i + 20), max_new_tokens=16)
               for i in range(8)]
        _drive(r, clock)
        assert all(h.status == RequestStatus.COMPLETED for h in hs2)
        assert r.stats["shed_outage"] == 0
        assert int(r._m_autoscale.labels("decode", "up").value) >= 2
    finally:
        r.close()


def test_prefill_tier_scales_to_zero_and_cold_starts(params, mesh1):
    """min_replicas=0 on the prefill tier: decode-only idle retires
    the last prefill replica; the next admission force-scales it back
    up (pending work, zero active) and completes token-exactly."""
    clock = _Clock()
    want = _reference(params, mesh1, [_prompt(8, 7)])[0]
    r = _tiered(params, mesh1,
                prefill_autoscale=AutoscalePolicy(
                    min_replicas=0, max_replicas=1, window=2,
                    cooldown_s=0.1),
                clock=clock)
    try:
        h0 = r.submit(_prompt(8, 1), max_new_tokens=12)
        _drive(r, clock)
        assert h0.done()
        for _ in range(40):
            r.tick()
            clock.advance(0.05)
        assert len(r._active_ctls("prefill")) == 0
        assert [c.state() for c in r._tier_ctls("prefill")] \
            == ["stopped"]
        h = r.submit(_prompt(8, 7), max_new_tokens=12)
        _drive(r, clock)
        np.testing.assert_array_equal(h.result(0), want)
        # the cold start revived the stopped replica (it may retire
        # again once the request's prefill is done — that's the
        # policy working, not a failure)
        ups = [e for e in r.autoscale_log
               if e["tier"] == "prefill" and e["direction"] == "up"]
        assert ups, "the pending admission never force-scaled up"
    finally:
        r.close()


# ---------------------------------------------------------------------------
# introspection + satellites
# ---------------------------------------------------------------------------

def test_debugz_tier_table_and_probe_piggyback(params, mesh1):
    """The per-tier debugz table (tier, states, occupancy, in-flight,
    last handoff) and the health-probe load piggyback: every probe
    carries slot_occupancy / tick_budget_utilization, so the router
    sees load without scraping /metrics."""
    r = _tiered(params, mesh1, pc=_ec(prefill_chunk=8))
    try:
        hs = [r.submit(_prompt(8, i), max_new_tokens=12)
              for i in range(3)]
        _drive(r)
        assert all(h.done() for h in hs)
        d = r.debugz()
        tiers = {row["tier"]: row for row in d["tiers"]}
        assert set(tiers) == {"prefill", "decode"}
        assert tiers["decode"]["replicas"] == 1
        assert tiers["prefill"]["occupancy"] is not None
        assert d["handoffs"]["ok"] == 3
        assert d["handoffs"]["last"]["outcome"] == "ok"
        assert tiers["prefill"]["last_handoff"] is not None
        # probe piggyback: the chunked prefill tier reports budget
        # utilization, every replica reports occupancy
        rows = {row["replica"]: row for row in d["replicas"]}
        assert all(row["slot_occupancy"] is not None
                   for row in rows.values())
        assert rows[0]["budget_utilization"] is not None
        assert rows[0]["tier"] == "prefill"
        h = r.health()
        assert set(h["tiers"]) == {"prefill", "decode"}
        # the engine health dict itself carries the piggyback fields
        eh = r._ctls[0].replica.engine.health()
        assert eh["slot_occupancy"] == 0.0
        assert eh["tick_budget_utilization"] is not None
    finally:
        r.close()


def test_flat_router_debugz_has_single_tier(params, mesh1):
    """The base Router grows the same table with one 'serving' tier
    (satellite: Router.debugz AND TieredRouter.debugz)."""
    from deeplearning4j_tpu.serving import Router
    r = Router(cfg=CFG, mesh=mesh1, params=params, num_replicas=2,
               engine_config=_ec(paged=False))
    try:
        h = r.submit(_prompt(), max_new_tokens=8)
        r.run_pending()
        assert h.done()
        d = r.debugz()
        assert [row["tier"] for row in d["tiers"]] == ["serving"]
        assert d["tiers"][0]["replicas"] == 2
        assert d["tiers"][0]["last_handoff"] is None
    finally:
        r.close()


def test_tier_config_parity_validated(params, mesh1):
    with pytest.raises(ValueError, match="temperature"):
        TieredRouter(cfg=CFG, mesh=mesh1, params=params,
                     prefill_engine_config=_ec(temperature=0.5),
                     decode_engine_config=_ec(temperature=0.0))


def test_committed_kv_pages_reporting(params, mesh1):
    """engine.committed_kv_pages — what fleet_worker.py now stamps on
    its progress lines — tracks the slot's page chain and zeroes on
    release."""
    eng = InferenceEngine(CFG, mesh1, params,
                          _ec(page_size=4, max_new_tokens=8))
    h = eng.submit(_prompt(10, 1), max_new_tokens=8, hold_kv=True)
    assert eng.committed_kv_pages(h) == 0        # not seated yet
    eng.run_pending()
    from deeplearning4j_tpu.serving.paging import pages_for
    assert eng.committed_kv_pages(h) == pages_for(10 + 8, 4)
    eng.release_held(h)
    assert eng.committed_kv_pages(h) == 0
    unpaged = InferenceEngine(CFG, mesh1, params, _ec(paged=False))
    h2 = unpaged.submit(_prompt(), max_new_tokens=4)
    unpaged.run_pending()
    assert unpaged.committed_kv_pages(h2) == 0
