"""Speculative decoding on the continuous engine (ISSUE-8).

The tentpole guarantees, each proven deterministically on the CPU
backend:

- EXACTNESS, stronger than the classic rejection-sampling bound: the
  speculative engine is TOKEN-IDENTICAL to the non-speculative engine
  on the same seed — greedy AND temperature/top-k sampled, float AND
  int8 KV, contiguous AND paged, for every drafter ("self", "int8",
  early-exit "layers:N"). Position-keyed sampling makes verification
  deterministic (accept a draft iff it equals the target's own
  position-keyed sample), so bit-identity — and therefore the
  rejection-sampling distributional guarantee — holds by construction.
- acceptance math: a draft identical to the target (draft="self")
  accepts 100% of its proposals at any temperature; budget caps
  truncate commits without breaking exactness.
- a POISONED draft pass can never corrupt committed KV: verification
  rejects every derailed draft, the round degrades to one committed
  token, `draft_rejected{poisoned}` forensics land in the flight
  recorder, and the adaptive-K controller falls back to K=1.
- adaptive K walks a CLOSED set of compiled programs (no steady-state
  recompiles) and converges to plain decode on adversarial
  (low-acceptance) traffic.
- paged pools: speculative writes are COW-privatized — a mid-draft
  rejection on a slot whose window spans a SHARED boundary page never
  perturbs the sharer's tokens.
"""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   generate, init_params)
from deeplearning4j_tpu.parallel.failure import ServingFaultInjector
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (EngineConfig, InferenceEngine,
                                        RequestStatus)
from deeplearning4j_tpu.serving.engine import (_compiled_paged_spec_decode,
                                               _compiled_spec_decode)

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


def _config(**kw):
    # max_new_tokens=11: after the prefill token, rem=10 = 2 * (K+1)
    # at the default spec_k=4 — full-acceptance runs never truncate a
    # round on the budget, so accepted == drafted is assertable
    base = dict(max_new_tokens=11, backoff_base_s=0.0,
                spec_decode=True, spec_k=4, draft="self")
    base.update(kw)
    return EngineConfig(**base)


def _run(params, mesh, econf, prompts, max_new=11):
    eng = InferenceEngine(CFG, mesh, params, econf)
    hs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run_pending()
    return eng, [h.result(0) for h in hs]


def _spec_counters(eng):
    d = eng.registry.get("serving_spec_drafted_tokens")._unlabeled()
    a = eng.registry.get("serving_spec_accepted_tokens")._unlabeled()
    return int(d.value), int(a.value)


# ---------------------------------------------------------------------------
# exactness + acceptance math
# ---------------------------------------------------------------------------

def test_greedy_self_draft_exact_with_full_acceptance(params, mesh1):
    """draft == target (draft='self'), greedy: every proposal matches
    the target's argmax, so acceptance is 100% and the output equals
    both the plain engine and single-chip generate byte for byte."""
    eng, got = _run(params, mesh1, _config(), [_prompt()])
    want = np.asarray(generate(CFG, params, _prompt()[None], 11,
                               key=jax.random.PRNGKey(0),
                               temperature=0.0))[0]
    np.testing.assert_array_equal(got[0], want)
    drafted, accepted = _spec_counters(eng)
    assert drafted == accepted == 8      # 2 rounds x K=4, none capped


@pytest.mark.parametrize("draft", ["int8", "layers:1"])
def test_greedy_imperfect_drafters_stay_exact(params, mesh1, draft):
    """An int8-quantized or early-exit drafter proposes WRONG tokens
    some of the time — verification corrects every divergence, so the
    committed stream is still bit-identical to plain decode (the
    drafter only moves the acceptance rate, never the tokens)."""
    _, want = _run(params, mesh1,
                   EngineConfig(max_new_tokens=11, decode_chunk=2),
                   [_prompt(8, s) for s in range(3)])
    eng, got = _run(params, mesh1, _config(draft=draft),
                    [_prompt(8, s) for s in range(3)])
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)
    drafted, accepted = _spec_counters(eng)
    assert 0 <= accepted <= drafted and drafted > 0


def test_sampled_spec_matches_nonspec_bit_exactly(params, mesh1):
    """Temperature + top-k sampling: the committed token at index j is
    ALWAYS sample(fold_in(key, j), target logits at j), so the
    speculative stream is bit-identical to the non-speculative one —
    which implies the rejection-sampling guarantee (the committed
    distribution IS the target distribution) in the strongest form.
    The early-exit drafter keeps acceptance partial, so mid-window
    rejection + resampling is genuinely exercised across seeds."""
    for seed in (0, 1, 2):
        prompts = [_prompt(8, seed), _prompt(10, seed + 5)]
        _, want = _run(params, mesh1,
                       EngineConfig(max_new_tokens=11, decode_chunk=2,
                                    temperature=0.9, top_k=5,
                                    seed=seed), prompts)
        eng, got = _run(params, mesh1,
                        _config(draft="layers:1", temperature=0.9,
                                top_k=5, seed=seed), prompts)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)


def test_budget_cap_truncates_commit_not_exactness(params, mesh1):
    """max_new_tokens not divisible by K+1: the final round commits
    only the remaining budget (rem caps the accepted prefix) and the
    result still equals the plain engine's, at exactly the budget."""
    _, want = _run(params, mesh1,
                   EngineConfig(max_new_tokens=9, decode_chunk=2),
                   [_prompt()], max_new=9)
    _, got = _run(params, mesh1, _config(max_new_tokens=9),
                  [_prompt()], max_new=9)
    np.testing.assert_array_equal(got[0], want[0])
    assert got[0].shape[0] == 8 + 9


def test_spec_int8_kv_and_quantized_weights_exact(params, mesh1):
    """Quant stack composition: int8 KV slot pool and int8 weights
    under speculation equal their non-speculative twins (the drafter
    IS the quantized tree when weights are quantized — zero extra
    HBM)."""
    for quant_kw in ({"kv_quantize": "int8"},
                     {"quantize": "int8", "kv_quantize": "int8"}):
        _, want = _run(params, mesh1,
                       EngineConfig(max_new_tokens=11, decode_chunk=2,
                                    **quant_kw), [_prompt()])
        _, got = _run(params, mesh1,
                      _config(draft="int8", **quant_kw), [_prompt()])
        np.testing.assert_array_equal(got[0], want[0])


def test_spec_on_data_model_mesh(params, devices8):
    """Speculative decode on a (data=2, model=2) mesh equals the 1x1
    run — slot sharding and the TP psum ride the same program."""
    mesh = make_mesh(MeshSpec(data=2, model=2))
    mesh1 = make_mesh(MeshSpec(data=1, model=1))
    prompts = [_prompt(8, s) for s in range(3)]
    _, want = _run(params, mesh1, _config(), prompts)
    _, got = _run(params, mesh, _config(), prompts)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


# ---------------------------------------------------------------------------
# paged: COW safety of speculative writes
# ---------------------------------------------------------------------------

def test_paged_spec_exact_with_prefix_hits(params, mesh1):
    """Paged + prefix cache + speculation: a second tenant hitting the
    cached system prompt maps the shared pages, and BOTH tenants'
    speculative streams equal the plain paged engine's."""
    sysp = (np.arange(16, dtype=np.int32) * 5) % CFG.vocab_size
    pa = np.concatenate([sysp, np.array([1, 2], np.int32)])
    pb = np.concatenate([sysp, np.array([3, 4], np.int32)])

    def staggered(econf):
        eng = InferenceEngine(CFG, mesh1, params, econf)
        ha = eng.submit(pa, max_new_tokens=8)
        eng.tick()                       # A prefills + seeds the cache
        hb = eng.submit(pb, max_new_tokens=8)
        eng.run_pending()
        return eng, ha.result(0), hb.result(0)

    base = dict(max_new_tokens=8, paged=True, page_size=8,
                max_batch_size=2)
    _, wa, wb = staggered(EngineConfig(decode_chunk=2, **base))
    eng, ga, gb = staggered(_config(spec_k=3, **base))
    np.testing.assert_array_equal(ga, wa)
    np.testing.assert_array_equal(gb, wb)
    hits = eng.registry.get(
        "serving_prefix_cache_hits")._unlabeled().value
    assert hits >= 1


def test_paged_cow_boundary_survives_mid_draft_rejection(params,
                                                         mesh1):
    """SATELLITE: the COW boundary page survives a mid-draft
    rejection. Tenant B fully hits tenant A's cached prompt (the
    boundary page is COW-copied at admission), then B's FIRST
    speculative round is draft-poisoned — every draft rejected, one
    corrected token committed, speculative garbage rows written and
    rolled over. A co-resident tenant C sharing the same prefix then
    admits and must reproduce its clean-run tokens exactly: the
    shared pages were never perturbed."""
    sysp = (np.arange(24, dtype=np.int32) * 7) % CFG.vocab_size
    base = dict(max_new_tokens=8, paged=True, page_size=8,
                max_batch_size=2)

    def run(inj=None):
        eng = InferenceEngine(
            CFG, mesh1, params, _config(spec_k=3, **base),
            fault_injector=inj)
        ha = eng.submit(sysp, max_new_tokens=8)
        eng.tick()                       # A caches the shared prompt
        hb = eng.submit(sysp, max_new_tokens=8)   # full-prefix hit
        eng.tick()
        hc = eng.submit(np.concatenate(
            [sysp[:16], np.array([9], np.int32)]), max_new_tokens=8)
        eng.run_pending()
        return eng, ha.result(0), hb.result(0), hc.result(0)

    _, wa, wb, wc = run()
    # poison B's first speculative round: B admits at step 2 (A's
    # prefill=0, A's first chunk=1), so its round is step 3
    inj = ServingFaultInjector(draft_poison_at={3: 2})
    eng, ga, gb, gc = run(inj)
    assert inj.drafts_poisoned == 1
    np.testing.assert_array_equal(ga, wa)
    np.testing.assert_array_equal(gb, wb)
    np.testing.assert_array_equal(gc, wc)


# ---------------------------------------------------------------------------
# fault injection: poisoned drafts
# ---------------------------------------------------------------------------

def test_draft_poison_never_corrupts_committed_kv(params, mesh1):
    """SATELLITE: a poisoned draft pass must never corrupt committed
    KV. The round's drafts are derailed on device, verification
    rejects them ALL, exactly one (target-verified) token commits,
    and the continuation stays byte-identical to the clean run —
    with draft_rejected{poisoned} forensics in the flight recorder
    and the controller falling back to K=1."""
    _, want = _run(params, mesh1, _config(), [_prompt()])
    inj = ServingFaultInjector(draft_poison_at={1: 1})
    eng = InferenceEngine(CFG, mesh1, params, _config(),
                          fault_injector=inj)
    h = eng.submit(_prompt())
    eng.tick()            # prefill (step 0) + the poisoned round (1)
    assert inj.drafts_poisoned == 1
    # the engine pipelines speculative rounds (ISSUE-19): the poisoned
    # round was DISPATCHED above; its forensics land at the commit
    # boundary one tick later
    eng.tick()
    ev = [e for e in h.trace.events if e.kind == "draft_rejected"]
    assert len(ev) == 1
    assert ev[0].data["poisoned"] is True and ev[0].data["drafted"] == 4
    # the poisoned round committed exactly the correction token, and
    # the controller dropped to K=1 for the next round
    chunk = [e for e in h.trace.events if e.kind == "decode_chunk"][0]
    assert chunk.data["accepted"] == 0 and chunk.data["tokens"] == 1
    assert eng.debugz()["spec"]["k"] == 1
    eng.run_pending()
    np.testing.assert_array_equal(h.result(0), want[0])


def test_adaptive_k_converges_to_plain_on_adversarial_traffic(
        params, mesh1):
    """Persistently poisoned drafts (the worst adversarial regime:
    acceptance pinned at 0): the controller walks K down to 1, then
    falls back to PLAIN decode for a cooldown — and the tokens still
    equal the clean run's. After the cooldown a probe round at K=1
    resumes speculation."""
    _, want = _run(params, mesh1,
                   EngineConfig(max_new_tokens=11, decode_chunk=2),
                   [_prompt()])
    inj = ServingFaultInjector(
        draft_poison_at={s: 1 for s in range(1, 40)})
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(max_new_tokens=11, spec_k=4),
                          fault_injector=inj)
    h = eng.submit(_prompt(), max_new_tokens=11)
    eng.run_pending()
    np.testing.assert_array_equal(h.result(0), want[0])
    spec = eng.debugz()["spec"]
    assert spec["k"] <= 1                 # backed off (or plain: 0)
    # a fresh request on an un-poisoned engine probes back up
    inj.draft_poison_at.clear()
    h2 = eng.submit(_prompt(8, 3), max_new_tokens=11)
    eng.run_pending()
    assert h2.status == RequestStatus.COMPLETED
    assert eng.debugz()["spec"]["k"] >= 1


# ---------------------------------------------------------------------------
# compile-cache discipline + metrics
# ---------------------------------------------------------------------------

def test_adaptive_k_walks_a_closed_program_set(params, mesh1):
    """Acceptance variance must never recompile: the controller only
    visits K in {spec_k, spec_k/2, .., 1}, so a second traffic wave
    adds ZERO spec-program cache entries."""
    from helpers import assert_no_recompiles
    base = _compiled_spec_decode.cache_info().currsize
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(draft="layers:1", spec_k=4))
    for s in range(3):
        eng.submit(_prompt(8, s))
    eng.run_pending()                     # walks K down as it rejects
    n0 = _compiled_spec_decode.cache_info().currsize
    with assert_no_recompiles(_compiled_spec_decode):
        for s in range(3, 8):
            eng.submit(_prompt(8 + s % 4, s))
        eng.run_pending()
    assert n0 - base <= 3                 # {4, 2, 1} at spec_k=4


def test_spec_metrics_published_and_lint_clean(params, mesh1):
    """serving_spec_{drafted,accepted}_tokens_total counters and the
    serving_spec_{acceptance_ratio,k} gauges publish into the engine
    registry, render in the Prometheus exposition, and honor the
    naming conventions test_metrics_naming.py lints (snake_case,
    _total on counters only, unitless gauges)."""
    import re

    from deeplearning4j_tpu.observability.export import prometheus_text

    eng, _ = _run(params, mesh1, _config(), [_prompt()])
    text = prometheus_text(eng.registry)
    assert "serving_spec_drafted_tokens_total 8" in text
    assert "serving_spec_accepted_tokens_total 8" in text
    assert "serving_spec_acceptance_ratio 1" in text
    assert "serving_spec_k 4" in text
    types = dict(
        line.split(" ", 3)[2:] for line in text.splitlines()
        if line.startswith("# TYPE "))
    assert types["serving_spec_drafted_tokens_total"] == "counter"
    assert types["serving_spec_accepted_tokens_total"] == "counter"
    assert types["serving_spec_acceptance_ratio"] == "gauge"
    assert types["serving_spec_k"] == "gauge"
    snake = re.compile(r"^[a-z][a-z0-9_]*$")
    for name, kind in types.items():
        assert snake.match(name)
        assert (kind == "counter") == name.endswith("_total")


def test_spec_off_keeps_registry_and_health_unchanged(params, mesh1):
    """A spec-off engine registers NO serving_spec_* series and its
    health dict merely gains the spec_decode=False flag."""
    eng, _ = _run(params, mesh1,
                  EngineConfig(max_new_tokens=11, decode_chunk=2),
                  [_prompt()])
    from deeplearning4j_tpu.observability.export import prometheus_text
    assert "serving_spec" not in prometheus_text(eng.registry)
    assert eng.health()["spec_decode"] is False
    assert "spec" not in eng.debugz()


# ---------------------------------------------------------------------------
# interaction: hot reload re-derives the drafter
# ---------------------------------------------------------------------------

def test_hot_reload_rebuilds_draft_tree(tmp_path, params, mesh1):
    """After a weight reload the drafter is re-derived from the NEW
    tree (a stale drafter would silently tank acceptance): the
    speculative engine's post-reload tokens equal a plain engine's
    post-reload tokens."""
    from deeplearning4j_tpu.util.checkpointing import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "w"), use_orbax=False)
    mgr.save_tree(params, 1)
    params2 = jax.tree_util.tree_map(lambda a: a * 0.5, params)
    mgr.save_tree(params2, 2)

    eng = InferenceEngine(CFG, mesh1, params, _config(draft="int8"))
    old_draft = eng._draft_params
    assert eng.reload_weights(mgr, step=2) == 2
    assert eng._draft_params is not old_draft
    h = eng.submit(_prompt())
    eng.run_pending()

    ref = InferenceEngine(CFG, mesh1, params2,
                          EngineConfig(max_new_tokens=11,
                                       decode_chunk=2))
    hr = ref.submit(_prompt())
    ref.run_pending()
    np.testing.assert_array_equal(h.result(0), hr.result(0))


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------

def test_spec_validation_errors(params, mesh1):
    with pytest.raises(ValueError, match="continuous"):
        InferenceEngine(CFG, mesh1, params,
                        _config(mode="batch"))
    with pytest.raises(ValueError, match="spec_k"):
        InferenceEngine(CFG, mesh1, params, _config(spec_k=0))
    with pytest.raises(ValueError, match="draft"):
        InferenceEngine(CFG, mesh1, params, _config(draft="layers:9"))
    with pytest.raises(ValueError, match="draft spec"):
        InferenceEngine(CFG, mesh1, params, _config(draft="turbo"))
    moe = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                            n_layers=2, max_len=64, n_experts=2)
    with pytest.raises(ValueError, match="MoE"):
        InferenceEngine(moe, mesh1,
                        init_params(moe, jax.random.PRNGKey(0)),
                        _config())
