"""Generate INDEPENDENT Keras import fixtures with real tf_keras.

VERDICT r1 #4: round-1 Keras-import goldens were self-authored (written
with h5py and verified against NumPy by the same author) — a systematic
layout misunderstanding would be invisible. These fixtures are produced
by GENUINE Keras (tf_keras, the Keras-2 lineage TensorFlow ships): the
HDF5 files come from `model.save(...)` and the golden outputs from
`model.predict(...)` — no code from this repository touches either.

Run offline (TF is not a runtime dependency of the framework):
    python tests/fixtures/generate_keras_fixtures.py
and check in the resulting .h5/.npz pairs (a few hundred KB).

The Keras-1 Theano fixture cannot be produced by modern Keras; its
model_config is hand-authored to the documented Keras-1 disk layout,
but its GOLDEN still comes from real Keras: a tf_keras channels_first
model is built with the same (OIHW→HWIO transposed) weights and
predicts the golden — so our importer's th path is checked against
Keras's own arithmetic, not ours.
"""
import json
import os

import numpy as np

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import tf_keras as keras  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
RNG = np.random.default_rng(20260730)


def _save(model, name, x):
    """Save model h5 + (input, keras-predicted golden) npz."""
    h5 = os.path.join(HERE, f"{name}.h5")
    model.save(h5, save_format="h5")
    y = model.predict(x, verbose=0)
    np.savez(os.path.join(HERE, f"{name}_golden.npz"), x=x, y=y)
    print(f"{name}: x{ x.shape } -> y{ y.shape }")


def mlp():
    m = keras.Sequential([
        keras.layers.Dense(16, activation="relu", input_shape=(8,)),
        keras.layers.Dense(12, activation="tanh"),
        keras.layers.Dense(5, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    _save(m, "real_mlp", RNG.normal(size=(7, 8)).astype(np.float32))


def cnn_tf():
    m = keras.Sequential([
        keras.layers.Conv2D(6, (3, 3), activation="relu",
                            input_shape=(12, 12, 2)),
        keras.layers.MaxPooling2D((2, 2)),
        keras.layers.Conv2D(4, (3, 3), padding="same", activation="relu"),
        keras.layers.Flatten(),
        keras.layers.Dense(9, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="adam")
    _save(m, "real_cnn", RNG.normal(size=(5, 12, 12, 2)).astype(np.float32))


def cnn_channels_first():
    """Keras-2 channels_first: NCHW activations, HWIO kernels — the
    combination that must NOT get a kernel transpose."""
    m = keras.Sequential([
        keras.layers.Conv2D(5, (3, 3), activation="relu",
                            data_format="channels_first",
                            input_shape=(2, 10, 10)),
        keras.layers.MaxPooling2D((2, 2), data_format="channels_first"),
        # the realistic Keras-2 pairing: Flatten(channels_first)
        # transposes to HWC before flattening (weight portability), so
        # the dense weights are HWC-ordered — NO import permutation.
        # The Dropout in between checks the exemption survives
        # order-preserving layers (inactive at inference).
        keras.layers.Flatten(data_format="channels_first"),
        keras.layers.Dropout(0.25),
        keras.layers.Dense(7, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="sgd")
    _save(m, "real_cnn_chfirst",
          RNG.normal(size=(4, 2, 10, 10)).astype(np.float32))


def lstm():
    m = keras.Sequential([
        keras.layers.LSTM(10, return_sequences=True,
                          input_shape=(6, 4)),
        keras.layers.LSTM(8, return_sequences=True),
        keras.layers.Dense(3, activation="softmax"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="rmsprop")
    _save(m, "real_lstm", RNG.normal(size=(3, 6, 4)).astype(np.float32))


def functional_merge():
    a = keras.Input(shape=(6,), name="in_a")
    b = keras.Input(shape=(6,), name="in_b")
    ha = keras.layers.Dense(10, activation="relu", name="da")(a)
    hb = keras.layers.Dense(10, activation="relu", name="db")(b)
    merged = keras.layers.Concatenate(name="cat")([ha, hb])
    added = keras.layers.Add(name="add")([ha, hb])
    m1 = keras.layers.Dense(4, activation="linear", name="head1")(merged)
    m2 = keras.layers.Dense(4, activation="linear", name="head2")(added)
    out = keras.layers.Add(name="out")([m1, m2])
    m = keras.Model([a, b], out)
    m.compile(loss="mse", optimizer="sgd")
    h5 = os.path.join(HERE, "real_functional.h5")
    m.save(h5, save_format="h5")
    xa = RNG.normal(size=(6, 6)).astype(np.float32)
    xb = RNG.normal(size=(6, 6)).astype(np.float32)
    y = m.predict([xa, xb], verbose=0)
    np.savez(os.path.join(HERE, "real_functional_golden.npz"),
             xa=xa, xb=xb, y=y)
    print(f"real_functional: -> y{y.shape}")


def keras1_theano_th():
    """Hand-authored Keras-1 'th' HDF5 (documented layout: list-form
    Sequential config, nb_filter/nb_row/nb_col/dim_ordering fields,
    <name>_W/<name>_b weight names, OIHW kernels, NO keras_version
    attribute — pre-1.0.8 files did not write one); golden predicted by
    real Keras via the equivalent channels_first model."""
    import h5py

    kh = kw = 3
    cin, cout = 2, 4
    W_oihw = RNG.normal(size=(cout, cin, kh, kw)).astype(np.float32) * 0.4
    b1 = RNG.normal(size=(cout,)).astype(np.float32) * 0.1
    dense_in = cout * 6 * 6
    W2 = RNG.normal(size=(dense_in, 5)).astype(np.float32) * 0.2
    b2 = RNG.normal(size=(5,)).astype(np.float32) * 0.1

    model_config = {
        "class_name": "Sequential",
        "config": [
            {"class_name": "Convolution2D", "config": {
                "name": "convolution2d_1", "nb_filter": cout,
                "nb_row": kh, "nb_col": kw, "subsample": [1, 1],
                "border_mode": "valid", "dim_ordering": "th",
                "activation": "relu",
                "batch_input_shape": [None, cin, 8, 8]}},
            {"class_name": "Flatten",
             "config": {"name": "flatten_1"}},
            {"class_name": "Dense", "config": {
                "name": "dense_1", "output_dim": 5,
                "activation": "softmax"}},
        ],
    }
    h5path = os.path.join(HERE, "real_keras1_th.h5")
    with h5py.File(h5path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_config)
        f.attrs["backend"] = "theano"
        g = f.create_group("model_weights")
        gc = g.create_group("convolution2d_1")
        gc.attrs["weight_names"] = np.array(
            [b"convolution2d_1_W", b"convolution2d_1_b"])
        gc.create_dataset("convolution2d_1_W", data=W_oihw)
        gc.create_dataset("convolution2d_1_b", data=b1)
        gd = g.create_group("dense_1")
        gd.attrs["weight_names"] = np.array(
            [b"dense_1_W", b"dense_1_b"])
        gd.create_dataset("dense_1_W", data=W2)
        gd.create_dataset("dense_1_b", data=b2)

    # golden from REAL keras: channels_first model, HWIO kernel. Keras-1
    # th flattened the raw NCHW tensor (C,H,W row-major) — tf_keras's
    # DEFAULT Flatten reshapes raw (no transpose; only
    # data_format="channels_first" triggers the to-HWC transpose), so a
    # plain Flatten reproduces Keras-1 ordering and W2 applies verbatim
    m = keras.Sequential([
        keras.layers.Conv2D(cout, (kh, kw), activation="relu",
                            data_format="channels_first",
                            input_shape=(cin, 8, 8)),
        keras.layers.Flatten(),
        keras.layers.Dense(5, activation="softmax"),
    ])
    W_hwio = np.transpose(W_oihw, (2, 3, 1, 0))
    m.layers[0].set_weights([W_hwio, b1])
    m.layers[2].set_weights([W2, b2])
    x_nchw = RNG.normal(size=(4, cin, 8, 8)).astype(np.float32)
    y = m.predict(x_nchw, verbose=0)
    np.savez(os.path.join(HERE, "real_keras1_th_golden.npz"),
             x=x_nchw, y=y)
    print(f"real_keras1_th: x{x_nchw.shape} -> y{y.shape}")


def resnet_residual():
    """Round-3 (VERDICT r2 #9): a REAL tf_keras functional residual
    model — Conv→BN→ReLU stem, two identity-shortcut residual blocks
    with BatchNorm, GlobalAveragePooling head. Briefly FIT so the BN
    moving statistics are genuinely estimated (non-trivial
    moving_mean/variance flow through the import), then golden =
    model.predict in inference mode."""
    x_in = keras.Input(shape=(12, 12, 3), name="img")
    h = keras.layers.Conv2D(8, (3, 3), padding="same",
                            name="stem_conv")(x_in)
    h = keras.layers.BatchNormalization(name="stem_bn")(h)
    h = keras.layers.Activation("relu", name="stem_relu")(h)
    for bi in range(2):
        s = h
        h = keras.layers.Conv2D(8, (3, 3), padding="same",
                                name=f"res{bi}_conv1")(h)
        h = keras.layers.BatchNormalization(name=f"res{bi}_bn1")(h)
        h = keras.layers.Activation("relu", name=f"res{bi}_relu1")(h)
        h = keras.layers.Conv2D(8, (3, 3), padding="same",
                                name=f"res{bi}_conv2")(h)
        h = keras.layers.BatchNormalization(name=f"res{bi}_bn2")(h)
        h = keras.layers.Add(name=f"res{bi}_add")([s, h])
        h = keras.layers.Activation("relu", name=f"res{bi}_out")(h)
    h = keras.layers.GlobalAveragePooling2D(name="gap")(h)
    out = keras.layers.Dense(4, activation="softmax", name="probs")(h)
    m = keras.Model(x_in, out)
    m.compile(loss="categorical_crossentropy", optimizer="adam")
    xs = RNG.normal(size=(64, 12, 12, 3)).astype(np.float32)
    ys = keras.utils.to_categorical(RNG.integers(0, 4, 64), 4)
    m.fit(xs, ys, epochs=2, batch_size=16, verbose=0)  # real BN stats
    h5 = os.path.join(HERE, "real_resnet_residual.h5")
    m.save(h5, save_format="h5")
    x = RNG.normal(size=(5, 12, 12, 3)).astype(np.float32)
    y = m.predict(x, verbose=0)
    np.savez(os.path.join(HERE, "real_resnet_residual_golden.npz"),
             x=x, y=y)
    print(f"real_resnet_residual: x{x.shape} -> y{y.shape}")


def trained_vgg16_head():
    """Round-3 (VERDICT r2 #8 'real pre-trained weights'): ImageNet
    checkpoints are unreachable (zero-egress container), so the
    real-weights fixture is a TRUNCATED VGG16 — blocks 1-2 of the real
    topology (64,64,pool,128,128,pool) + a small dense head — actually
    TRAINED by tf_keras on sklearn's digits images until it fits. The
    weights are therefore real trained weights produced entirely
    outside this repository; the golden records predictions AND the
    training labels so the import test can verify genuine accuracy,
    not just numeric agreement."""
    from sklearn.datasets import load_digits

    digits = load_digits()
    # 8x8 grayscale -> 16x16x3 (VGG16 wants 3 channels; upsample 2x)
    imgs = digits.images.astype(np.float32) / 16.0
    imgs = np.repeat(np.repeat(imgs, 2, axis=1), 2, axis=2)
    x_all = np.stack([imgs] * 3, axis=-1)
    y_all = digits.target
    m = keras.Sequential([
        keras.layers.Conv2D(64, (3, 3), padding="same",
                            activation="relu",
                            input_shape=(16, 16, 3),
                            name="block1_conv1"),
        keras.layers.Conv2D(64, (3, 3), padding="same",
                            activation="relu", name="block1_conv2"),
        keras.layers.MaxPooling2D((2, 2), name="block1_pool"),
        keras.layers.Conv2D(128, (3, 3), padding="same",
                            activation="relu", name="block2_conv1"),
        keras.layers.Conv2D(128, (3, 3), padding="same",
                            activation="relu", name="block2_conv2"),
        keras.layers.MaxPooling2D((2, 2), name="block2_pool"),
        keras.layers.Flatten(name="flatten"),
        keras.layers.Dense(64, activation="relu", name="fc1"),
        keras.layers.Dense(10, activation="softmax",
                           name="predictions"),
    ])
    m.compile(loss="sparse_categorical_crossentropy", optimizer="adam",
              metrics=["accuracy"])
    m.fit(x_all[:1500], y_all[:1500], epochs=4, batch_size=64,
          verbose=0)
    acc = float(m.evaluate(x_all[1500:], y_all[1500:],
                           verbose=0)[1])
    h5 = os.path.join(HERE, "real_vgg16_trained.h5")
    m.save(h5, save_format="h5")
    x = x_all[1500:1520]
    y = m.predict(x, verbose=0)
    np.savez(os.path.join(HERE, "real_vgg16_trained_golden.npz"),
             x=x, y=y, labels=y_all[1500:1520], keras_test_acc=acc)
    print(f"real_vgg16_trained: keras holdout acc {acc:.3f}")


if __name__ == "__main__":
    mlp()
    cnn_tf()
    cnn_channels_first()
    lstm()
    functional_merge()
    keras1_theano_th()
    resnet_residual()
    trained_vgg16_head()
