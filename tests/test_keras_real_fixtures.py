"""End-to-end Keras import against INDEPENDENT goldens.

VERDICT r1 #4 closure. The checked-in fixtures under tests/fixtures/
were produced by genuine Keras (tf_keras `model.save` + `model.predict`
— see tests/fixtures/generate_keras_fixtures.py); none of this repo's
code touched the files or the goldens. This is the reference's
KerasModelEndToEndTest methodology (independently generated fixtures
from dl4j-test-resources) rather than round-1's self-authored ones.

Input-layout contract for channels_first/th models: the imported
framework model is NHWC-native (README component map row 'Config DSL'),
so NCHW fixture inputs are fed transposed — the model function itself
must match Keras's output exactly.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.keras import (
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights)

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures")


def _fixture(name):
    h5 = os.path.join(FIXDIR, f"{name}.h5")
    gz = os.path.join(FIXDIR, f"{name}_golden.npz")
    if not (os.path.exists(h5) and os.path.exists(gz)):
        pytest.skip(f"fixture {name} not generated")
    return h5, dict(np.load(gz))


def test_real_mlp_golden():
    h5, g = _fixture("real_mlp")
    net = import_keras_sequential_model_and_weights(h5)
    got = np.asarray(net.output(g["x"]))
    np.testing.assert_allclose(got, g["y"], rtol=1e-4, atol=1e-5)


def test_real_cnn_golden():
    h5, g = _fixture("real_cnn")
    net = import_keras_sequential_model_and_weights(h5)
    got = np.asarray(net.output(g["x"]))
    np.testing.assert_allclose(got, g["y"], rtol=1e-4, atol=1e-5)


def test_real_cnn_channels_first_golden():
    """Keras-2 channels_first: HWIO kernels (no transpose!) + NCHW
    activations; fed NHWC to the NHWC-native import."""
    h5, g = _fixture("real_cnn_chfirst")
    net = import_keras_sequential_model_and_weights(h5)
    x_nhwc = np.transpose(g["x"], (0, 2, 3, 1))
    got = np.asarray(net.output(x_nhwc))
    np.testing.assert_allclose(got, g["y"], rtol=1e-4, atol=1e-5)


def test_real_lstm_golden():
    h5, g = _fixture("real_lstm")
    net = import_keras_sequential_model_and_weights(h5)
    got = np.asarray(net.output(g["x"]))
    np.testing.assert_allclose(got, g["y"], rtol=1e-4, atol=1e-4)


def test_real_functional_golden():
    h5, g = _fixture("real_functional")
    net = import_keras_model_and_weights(h5)
    out = net.output({"in_a": g["xa"], "in_b": g["xb"]})
    if isinstance(out, dict):
        out = list(out.values())
    got = np.asarray(out[0])
    np.testing.assert_allclose(got, g["y"], rtol=1e-4, atol=1e-5)


def test_real_keras1_th_golden():
    """Keras-1 Theano file (hand-authored to the documented layout: OIHW
    kernels, list-form config, <name>_W weight names, no keras_version
    attr) — golden predicted by real Keras via the equivalent
    channels_first model."""
    h5, g = _fixture("real_keras1_th")
    net = import_keras_sequential_model_and_weights(h5)
    x_nhwc = np.transpose(g["x"], (0, 2, 3, 1))
    got = np.asarray(net.output(x_nhwc))
    np.testing.assert_allclose(got, g["y"], rtol=1e-4, atol=1e-5)


def test_real_resnet_residual_golden():
    """Round-3 (VERDICT r2 #9): functional residual model with skip
    connections (Add vertices) and FITTED BatchNormalization moving
    statistics, generated and predicted by real tf_keras — the
    ResNet-class import path (reference: KerasModelImport.java:101
    functional branch + BN/Merge mappers)."""
    h5, g = _fixture("real_resnet_residual")
    net = import_keras_model_and_weights(h5)
    out = net.output({"img": g["x"]})
    if isinstance(out, dict):
        out = list(out.values())
    got = np.asarray(out[0])
    np.testing.assert_allclose(got, g["y"], rtol=1e-4, atol=1e-5)


def test_real_vgg16_trained_weights_predict():
    """Round-3 (VERDICT r2 missing #1 'real pre-trained weights'):
    weights REALLY TRAINED by tf_keras (truncated VGG16 topology,
    sklearn digits, 91.6% keras holdout accuracy — ImageNet checkpoints
    are unreachable from this zero-egress container, recorded in
    BASELINE.md) flow through the model-zoo loader
    (trained_models.load_vgg16 → KerasModelImport path) and must
    reproduce Keras's predictions AND genuinely classify: the import
    must agree with the recorded true labels wherever Keras did."""
    from deeplearning4j_tpu.modelimport.trained_models import load_vgg16

    h5, g = _fixture("real_vgg16_trained")
    net = load_vgg16(h5)
    got = np.asarray(net.output(g["x"]))
    np.testing.assert_allclose(got, g["y"], rtol=1e-3, atol=1e-4)
    pred = got.argmax(1)
    keras_pred = g["y"].argmax(1)
    np.testing.assert_array_equal(pred, keras_pred)
    # real accuracy on real data, through our forward pass
    acc = float((pred == g["labels"]).mean())
    assert acc >= 0.8, acc


@pytest.mark.slow
def test_full_resnet50_import_matches_keras():
    """The BASELINE north-star model end-to-end: the FULL
    tf_keras.applications.ResNet50 (177 layers: strided convs,
    ZeroPadding, BatchNorm, Add shortcuts with projection branches,
    GlobalAveragePooling) built in-process, saved to HDF5, imported
    through the functional path, predictions compared to Keras's own.
    Generated at test time (no fixture checked in: the h5 is ~100MB),
    skipped where tf_keras is unavailable. Reference:
    KerasModelImport.java:101 + BASELINE.md config 2."""
    keras = pytest.importorskip("tf_keras")
    import tempfile

    m = keras.applications.ResNet50(weights=None, input_shape=(64, 64, 3),
                                    classes=7)
    h5 = tempfile.mktemp(suffix=".h5")
    try:
        m.save(h5, save_format="h5")
        x = np.random.default_rng(0).normal(
            size=(2, 64, 64, 3)).astype(np.float32)
        want = m.predict(x, verbose=0)
        net = import_keras_model_and_weights(h5)
        input_name = m.layers[0].name
        out = net.output({input_name: x})
        if isinstance(out, dict):
            out = list(out.values())
        got = np.asarray(out[0])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    finally:
        if os.path.exists(h5):
            os.remove(h5)
