"""Data pipeline tests: record readers, fetchers, iterator wrappers.

Models the reference's iterator/datavec tests
(deeplearning4j-core/src/test/.../datasets/).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.impl import (CifarDataSetIterator,
                                              LFWDataSetIterator,
                                              MnistDataSetIterator)
from deeplearning4j_tpu.datasets.iterators import (AsyncDataSetIterator,
                                                   DataSet,
                                                   IteratorDataSetIterator,
                                                   MultipleEpochsIterator,
                                                   SamplingDataSetIterator,
                                                   ViewIterator)
from deeplearning4j_tpu.datasets.records import (
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    ImageRecordReader, MultiDataSet, RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator, SequenceRecordReaderDataSetIterator)


def test_csv_record_reader(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("h1,h2,label\n1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,2\n")
    reader = CSVRecordReader(str(p), skip_lines=1)
    it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=-1,
                                     num_classes=3)
    batch = next(iter(it))
    assert batch.features.shape == (2, 2)
    np.testing.assert_allclose(batch.features[0], [1.0, 2.0])
    np.testing.assert_allclose(batch.labels[0], [1, 0, 0])
    assert it.total_outcomes() == 3


def test_csv_record_reader_regression(tmp_path):
    p = tmp_path / "reg.csv"
    p.write_text("1,2,0.5\n3,4,0.7\n")
    it = RecordReaderDataSetIterator(CSVRecordReader(str(p)), 2,
                                     regression=True)
    b = next(iter(it))
    assert b.labels.shape == (2, 1)
    np.testing.assert_allclose(b.labels[:, 0], [0.5, 0.7])


def test_sequence_record_reader_masks(tmp_path):
    p1 = tmp_path / "seq1.csv"
    p1.write_text("1,2,0\n3,4,1\n5,6,0\n")   # T=3
    p2 = tmp_path / "seq2.csv"
    p2.write_text("7,8,1\n")                  # T=1 → padded+masked
    reader = CSVSequenceRecordReader([str(p1), str(p2)])
    it = SequenceRecordReaderDataSetIterator(reader, batch_size=2,
                                             num_classes=2)
    b = next(iter(it))
    assert b.features.shape == (2, 3, 2)
    assert b.features_mask.tolist() == [[1, 1, 1], [1, 0, 0]]
    np.testing.assert_allclose(b.labels[1, 0], [0, 1])
    assert b.labels[1, 1].sum() == 0  # padded step


def test_image_record_reader_npy(tmp_path):
    for cls in ("cats", "dogs"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            np.save(d / f"{i}.npy",
                    np.full((4, 4, 1), 0.5, np.float32))
    reader = ImageRecordReader(4, 4, 1)
    reader.initialize(str(tmp_path))
    assert reader.labels == ["cats", "dogs"]
    it = RecordReaderDataSetIterator(reader, batch_size=6, num_classes=2)
    b = next(iter(it))
    assert b.features.shape == (6, 4, 4, 1)
    assert b.labels.sum(0).tolist() == [3, 3]


def test_multi_dataset_iterator():
    recs_a = [[1.0, 2.0, 0], [3.0, 4.0, 1]]
    builder = (RecordReaderMultiDataSetIterator.Builder(batch_size=2)
               .add_reader("r", CollectionRecordReader(recs_a))
               .add_input("r", 0, 1)
               .add_output_one_hot("r", 2, 2))
    mds = next(iter(builder.build()))
    assert isinstance(mds, MultiDataSet)
    assert mds.features[0].shape == (2, 2)
    assert mds.labels[0].shape == (2, 2)


def test_cifar_lfw_shapes():
    cifar = CifarDataSetIterator(batch_size=8, num_examples=32)
    b = next(iter(cifar))
    assert b.features.shape == (8, 32, 32, 3)
    assert b.labels.shape == (8, 10)
    lfw = LFWDataSetIterator(batch_size=4, num_examples=16, height=32,
                             width=32)
    b = next(iter(lfw))
    assert b.features.shape == (4, 32, 32, 3)


def test_sampling_and_view_iterators():
    ds = DataSet(np.arange(20, dtype=np.float32).reshape(10, 2),
                 np.eye(2, dtype=np.float32)[np.arange(10) % 2])
    samp = SamplingDataSetIterator(ds, batch_size=4, total_batches=3,
                                   seed=0)
    batches = list(samp)
    assert len(batches) == 3 and batches[0].features.shape == (4, 2)
    view = ViewIterator(ds, batch_size=4)
    sizes = [b.features.shape[0] for b in view]
    assert sizes == [4, 4, 2]


def test_iterator_dataset_iterator_and_async():
    def gen():
        for i in range(5):
            yield DataSet(np.full((2, 3), i, np.float32),
                          np.zeros((2, 1), np.float32))
    it = IteratorDataSetIterator(gen)
    vals = [b.features[0, 0] for b in it]
    assert vals == [0, 1, 2, 3, 4]
    it.reset()
    async_it = AsyncDataSetIterator(it, queue_size=2)
    vals2 = [b.features[0, 0] for b in async_it]
    assert vals2 == [0, 1, 2, 3, 4]


def test_async_reiteration_joins_stale_worker():
    """ISSUE-2 regression: re-iterating while a previous epoch's
    producer thread is still alive (e.g. the consumer abandoned the
    epoch early) must drain + join it, not leak a second producer
    into a fresh queue."""
    from deeplearning4j_tpu.datasets.iterators import BaseDatasetIterator
    base = BaseDatasetIterator(
        np.arange(40, dtype=np.float32).reshape(20, 2),
        np.zeros((20, 1), np.float32), 2)
    it = AsyncDataSetIterator(base, queue_size=1)
    first = iter(it)
    next(first)                      # worker alive, blocked on put
    stale = it._thread
    assert stale is not None and stale.is_alive()

    second = iter(it)                # must join the stale producer
    assert not stale.is_alive()
    assert it._thread is not stale
    # the fresh epoch is complete — no batches stolen by the old worker
    assert len(list(second)) == 10
    # and a clean third epoch still works
    assert len(list(iter(it))) == 10
    it._thread.join(timeout=5)
    assert not it._thread.is_alive()


def test_mnist_iterator_shapes():
    it = MnistDataSetIterator(batch_size=16, num_examples=64)
    b = next(iter(it))
    assert b.features.shape == (16, 784)
    assert b.labels.shape == (16, 10)


def test_reconstruction_and_moving_window():
    from deeplearning4j_tpu.datasets.iterators import (
        BaseDatasetIterator, MovingWindowDataSetIterator,
        ReconstructionDataSetIterator)
    rng = np.random.default_rng(0)
    f = rng.random((6, 8, 8, 1)).astype(np.float32)
    l = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 6)]
    rec = ReconstructionDataSetIterator(BaseDatasetIterator(
        f.reshape(6, -1), l, 3))
    b = next(iter(rec))
    np.testing.assert_allclose(b.features, b.labels)
    mw = MovingWindowDataSetIterator(DataSet(f, l), batch_size=8,
                                     window_h=4, window_w=4)
    b = next(iter(mw))
    assert b.features.shape == (8, 4, 4, 1)


def test_iterator_longtail_parity():
    """AbstractDataSetIterator aliases, preprocessor chaining, multi
    adapters (reference: datasets/iterator/{AbstractDataSetIterator,
    CombinedPreProcessor,DummyPreProcessor,IteratorMultiDataSetIterator,
    impl/SingletonMultiDataSetIterator,impl/MultiDataSetIteratorAdapter})."""
    from deeplearning4j_tpu.datasets.iterators import (
        AbstractDataSetIterator, CombinedPreProcessor, DataSet,
        DoublesDataSetIterator, DummyPreProcessor, FloatsDataSetIterator,
        INDArrayDataSetIterator, IteratorMultiDataSetIterator,
        ListDataSetIterator, MultiDataSetIteratorAdapter,
        SingletonMultiDataSetIterator)
    from deeplearning4j_tpu.datasets.records import MultiDataSet

    pairs = [(np.full(3, i, np.float32), np.eye(2, dtype=np.float32)[i % 2])
             for i in range(6)]
    it = AbstractDataSetIterator(pairs, batch_size=4)
    batches = list(it)
    assert [b.num_examples() for b in batches] == [4, 2]
    assert FloatsDataSetIterator is AbstractDataSetIterator
    assert DoublesDataSetIterator is INDArrayDataSetIterator

    class AddOne:
        def pre_process(self, ds):
            return DataSet(ds.features + 1, ds.labels)

    chain = CombinedPreProcessor(DummyPreProcessor(), AddOne(), AddOne())
    out = chain.pre_process(DataSet(np.zeros((2, 3)), np.zeros((2, 2))))
    assert float(out.features.max()) == 2.0

    mds = MultiDataSet(features=[np.ones((4, 2))], labels=[np.zeros((4, 1))])
    single = SingletonMultiDataSetIterator(mds)
    assert len(list(single)) == 1 and len(list(single)) == 1  # resets
    multi = IteratorMultiDataSetIterator([mds, mds])
    assert len(list(multi)) == 2

    base = ListDataSetIterator(
        [DataSet(np.ones((6, 2), np.float32),
                 np.zeros((6, 2), np.float32))], batch_size=3)
    adapted = list(MultiDataSetIteratorAdapter(base))
    assert len(adapted) == 2
    assert isinstance(adapted[0], MultiDataSet)
    assert adapted[0].features[0].shape == (3, 2)


def test_iterator_wrapper_edge_cases():
    """Review-hardened paths: one-shot generators refuse silent empty
    epochs; empty pair sources construct; adapter masks survive."""
    from deeplearning4j_tpu.datasets.iterators import (
        AbstractDataSetIterator, DataSet, IteratorMultiDataSetIterator,
        MultiDataSetIteratorAdapter, ListDataSetIterator)
    from deeplearning4j_tpu.datasets.records import MultiDataSet
    from deeplearning4j_tpu.nn.multilayer import _unpack_batch

    gen = (MultiDataSet(features=[np.ones((2, 2))],
                        labels=[np.zeros((2, 1))]) for _ in range(2))
    it = IteratorMultiDataSetIterator(gen)
    assert len(list(it)) == 2
    with pytest.raises(ValueError, match="one-shot"):
        list(it)

    empty = AbstractDataSetIterator([], batch_size=4)
    assert list(empty) == []

    class _MaskedIter:
        def __iter__(self):
            yield DataSet(np.ones((2, 3, 4)), np.zeros((2, 3, 2)),
                          features_mask=np.array([[1, 1, 0], [1, 0, 0]],
                                                 np.float32))
        def reset(self):
            pass

    mds = next(iter(MultiDataSetIteratorAdapter(_MaskedIter())))
    feats, labs, fmask, lmask = _unpack_batch(mds)
    assert fmask is not None and np.asarray(fmask[0]).shape == (2, 3)
