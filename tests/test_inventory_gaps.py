"""Tests for the SURVEY §2 long-tail items: Node2Vec, eval/meta
prediction tracking, Curves dataset, ParamAndGradientIterationListener."""
import numpy as np

from deeplearning4j_tpu.graph import (Graph, Node2Vec, Node2VecWalkIterator)


def _two_cliques(k: int = 5) -> Graph:
    """Two k-cliques joined by one bridge edge — communities the
    embedding must separate."""
    g = Graph(2 * k)
    for base in (0, k):
        for i in range(k):
            for j in range(i + 1, k):
                g.add_edge(base + i, base + j)
    g.add_edge(k - 1, k)
    return g


def test_node2vec_walks_respect_pq_bias():
    g = _two_cliques(4)
    # q >> 1: walks stay local (BFS-like) — from within a clique, the
    # fraction of steps leaving the start community should be small
    it = Node2VecWalkIterator(g, walk_length=20, p=1.0, q=4.0, seed=3)
    cross = total = 0
    for walk in it:
        com = 0 if walk[0] < 4 else 1
        for v in walk[1:]:
            total += 1
            if (0 if v < 4 else 1) != com:
                cross += 1
    assert total > 0
    assert cross / total < 0.5


def test_node2vec_embeddings_separate_communities():
    g = _two_cliques(5)
    n2v = Node2Vec(vector_size=16, window_size=3, walk_length=12,
                   walks_per_vertex=6, p=0.5, q=2.0, seed=11,
                   learning_rate=0.05, epochs=12, negative=3)
    n2v.fit_graph(g)
    # mean intra-community similarity must exceed inter-community
    intra, inter = [], []
    for a in range(10):
        for b in range(a + 1, 10):
            s = n2v.similarity_vertices(a, b)
            (intra if (a < 5) == (b < 5) else inter).append(s)
    assert np.mean(intra) > np.mean(inter)


def test_evaluation_prediction_meta_tracking():
    from deeplearning4j_tpu.eval import Evaluation, RecordMetaData

    labels = np.eye(3)[[0, 1, 2, 1]]
    # record 3 (actual 1) is misclassified as 2
    preds = np.asarray([[0.9, 0.05, 0.05],
                        [0.1, 0.8, 0.1],
                        [0.1, 0.1, 0.8],
                        [0.1, 0.2, 0.7]])
    meta = [RecordMetaData(uri="file.csv", index=i) for i in range(4)]
    ev = Evaluation()
    ev.eval(labels, preds, metadata=meta)
    errors = ev.get_prediction_errors()
    assert len(errors) == 1
    assert errors[0].actual_class == 1
    assert errors[0].predicted_class == 2
    assert errors[0].record_meta_data.index == 3
    assert "file.csv:3" in errors[0].record_meta_data.get_location()
    assert len(ev.get_predictions_by_actual_class(1)) == 2
    assert len(ev.get_predictions_by_predicted_class(2)) == 2
    assert len(ev.get_predictions(1, 2)) == 1


def test_curves_iterator_shapes_and_reconstruction_targets():
    from deeplearning4j_tpu.datasets import CurvesDataSetIterator

    it = CurvesDataSetIterator(batch_size=32, num_examples=96)
    batches = list(it)
    assert len(batches) == 3
    b = batches[0]
    assert b.features.shape == (32, 784)
    np.testing.assert_array_equal(b.features, b.labels)
    # curves are sparse binary strokes
    assert 0 < b.features.mean() < 0.3
    # deterministic across constructions
    it2 = CurvesDataSetIterator(batch_size=32, num_examples=96)
    np.testing.assert_array_equal(batches[0].features,
                                  next(iter(it2)).features)


def test_param_and_gradient_listener(tmp_path):
    from deeplearning4j_tpu.nn.conf.configuration import (
        NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train.listeners import (
        ParamAndGradientIterationListener)

    path = str(tmp_path / "pg.tsv")
    conf = (NeuralNetConfiguration(seed=1, updater="sgd",
                                   learning_rate=0.1)
            .list(DenseLayer(n_in=4, n_out=6, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax",
                              loss_function="mcxent")))
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(ParamAndGradientIterationListener(
        file_path=path, print_to_log=False))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 4)).astype(np.float32)
    y = np.eye(2)[rng.integers(0, 2, 16)].astype(np.float32)
    for _ in range(3):
        net.fit(x, y)
    lines = open(path).read().strip().splitlines()
    assert lines[0].startswith("iteration\tscore")
    assert len(lines) == 4  # header + 3 iterations
    last = lines[-1].split("\t")
    assert float(last[2]) > 0          # param mean |.|
    assert float(last[3]) > 0          # update mean |.|
