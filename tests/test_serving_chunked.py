"""Chunked prefill + token-budget scheduler (ISSUE-10).

The tentpole guarantees, each proven deterministically on the CPU
backend:

- token exactness: chunked prefill == one-shot prefill == single-chip
  `generate`, byte for byte, for every chunk size — greedy AND
  sampled, float AND int8 KV, contiguous AND paged, fresh AND
  prefix-hit-resume admissions;
- the TPOT-stall bound, by name: while a max-length prompt prefills,
  co-resident decoding slots advance EVERY tick and no inter-token gap
  exceeds ceil(tick_token_budget / prefill_chunk) + 1 compiled-call
  latencies (injected call-count clock);
- zero steady-state recompiles: ONE chunked-prefill program per
  (prefill_chunk, num_slots) geometry serves every prompt length —
  resume position, valid length, and final-chunk flag are runtime
  data (guard: helpers.assert_no_recompiles);
- legacy preservation: prefill_chunk=None engines never touch the
  chunked program caches and keep the PR-4/7/8 cache keys;
- mid-prefill fault forensics: a slot that dies, preempts, cancels,
  or deadlines MID-PREFILL resolves exactly like a mid-decode one —
  isolation re-runs it solo from its committed prefix, co-resident
  decoding slots never even see the failing call.
"""
import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   generate, init_params)
from deeplearning4j_tpu.observability.events import FlightRecorder
from deeplearning4j_tpu.parallel.failure import (ServingFaultInjector,
                                                 TrainingFailure)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (EngineConfig, InferenceEngine,
                                        RequestStatus)
from deeplearning4j_tpu.serving.engine import (
    DeadlineExceeded, RequestCancelled, RequestQuarantined,
    _compiled_chunked_prefill, _compiled_decode_chunk,
    _compiled_paged_chunked_prefill, _compiled_prefill)
from helpers import assert_no_recompiles

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


def _config(**kw):
    base = dict(decode_chunk=2, max_new_tokens=6, backoff_base_s=0.0,
                prefill_chunk=8)
    base.update(kw)
    return EngineConfig(**base)


def _solo(params, mesh, prompt, max_new, **cfg_kw):
    """One-shot (legacy) reference engine run for ``prompt``."""
    eng = InferenceEngine(CFG, mesh, params,
                          _config(prefill_chunk=None,
                                  max_new_tokens=max_new, **cfg_kw))
    h = eng.submit(prompt, max_new_tokens=max_new)
    eng.run_pending()
    return h.result(0)


# ---------------------------------------------------------------------------
# token exactness: chunked == one-shot == single-chip generate
# ---------------------------------------------------------------------------

def test_chunked_matches_oneshot_and_generate(params, mesh1):
    """Every chunk size — including chunks that straddle the prompt
    unevenly and a chunk larger than the prompt — reproduces the
    one-shot engine AND single-chip `generate` byte for byte."""
    want = np.asarray(generate(CFG, params, _prompt(24)[None], 6,
                               key=jax.random.PRNGKey(0),
                               temperature=0.0))[0]
    for chunk in (3, 8, 24, 40):
        eng = InferenceEngine(CFG, mesh1, params,
                              _config(prefill_chunk=chunk))
        h = eng.submit(_prompt(24))
        eng.run_pending()
        np.testing.assert_array_equal(h.result(0), want)
    np.testing.assert_array_equal(
        _solo(params, mesh1, _prompt(24), 6), want)


def test_chunked_sampled_continuations_bit_identical(params, mesh1):
    """Sampled decode (temperature + top-k) is chunk-invariant: the
    position-keyed sampling schedule depends on absolute sequence
    position only, so the first token sampled at index plen matches
    whatever chunk boundary produced it."""
    kw = dict(temperature=0.8, top_k=5, max_new_tokens=8)
    ref = _solo(params, mesh1, _prompt(20, 2), 8, temperature=0.8,
                top_k=5)
    for chunk in (4, 7):
        eng = InferenceEngine(CFG, mesh1, params,
                              _config(prefill_chunk=chunk, **kw))
        h = eng.submit(_prompt(20, 2))
        eng.run_pending()
        np.testing.assert_array_equal(h.result(0), ref)


def test_chunked_int8_kv_token_exact(params, mesh1):
    """int8 KV: later chunks re-read the prefix through its
    quantization exactly as decode does, so the chunked int8 engine
    matches the one-shot int8 engine token for token."""
    ref = _solo(params, mesh1, _prompt(24, 1), 6, kv_quantize="int8")
    for chunk in (5, 12):
        eng = InferenceEngine(CFG, mesh1, params,
                              _config(prefill_chunk=chunk,
                                      kv_quantize="int8"))
        h = eng.submit(_prompt(24, 1))
        eng.run_pending()
        np.testing.assert_array_equal(h.result(0), ref)


def test_chunked_paged_fresh_and_prefix_hit_resume(params, mesh1):
    """Paged pool: a fresh chunked admission matches the one-shot
    paged engine, and a PREFIX-HIT admission — whose chunked prefill
    resumes from the radix-cache boundary, which is not a chunk
    boundary — still matches byte for byte."""
    ref = _solo(params, mesh1, _prompt(24), 6, paged=True, page_size=4)
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(prefill_chunk=5, paged=True,
                                  page_size=4))
    fresh = eng.submit(_prompt(24))
    eng.run_pending()
    np.testing.assert_array_equal(fresh.result(0), ref)
    hit = eng.submit(_prompt(24))          # radix full-prefix hit
    eng.run_pending()
    np.testing.assert_array_equal(hit.result(0), ref)
    assert eng.registry.get("serving_prefix_cache_hits").value >= 1
    # int8 paged chunked, fresh + hit
    ref8 = _solo(params, mesh1, _prompt(24), 6, paged=True,
                 page_size=4, kv_quantize="int8")
    eng8 = InferenceEngine(CFG, mesh1, params,
                           _config(prefill_chunk=5, paged=True,
                                   page_size=4, kv_quantize="int8"))
    for _ in range(2):
        h = eng8.submit(_prompt(24))
        eng8.run_pending()
        np.testing.assert_array_equal(h.result(0), ref8)


def test_chunked_on_data_model_mesh(params, devices8):
    """Chunked prefill shards like the one-shot pool (slots over
    'data', heads over 'model'): 2x2-mesh results equal the 1x1 runs."""
    mesh = make_mesh(MeshSpec(data=2, model=2))
    mesh1 = make_mesh(MeshSpec(data=1, model=1))
    eng = InferenceEngine(CFG, mesh, params, _config(prefill_chunk=6))
    hs = [eng.submit(_prompt(8 + 4 * i, i)) for i in range(3)]
    eng.run_pending()
    for h in hs:
        np.testing.assert_array_equal(
            h.result(0), _solo(params, mesh1, h.prompt, 6))


# ---------------------------------------------------------------------------
# compile discipline
# ---------------------------------------------------------------------------

def test_chunked_zero_steady_state_recompiles(params, mesh1):
    """ONE chunked-prefill program per (prefill_chunk, num_slots)
    geometry covers EVERY prompt length — even lengths that would land
    in different one-shot buckets — because resume position and valid
    length are runtime data. After the warm-up request, a wave of
    mixed lengths compiles nothing."""
    eng = InferenceEngine(CFG, mesh1, params, _config())
    eng.submit(_prompt(8))
    eng.run_pending()
    with assert_no_recompiles(_compiled_chunked_prefill,
                              _compiled_decode_chunk):
        for t0, seed in [(9, 1), (24, 2), (40, 3), (13, 4), (56, 5)]:
            eng.submit(_prompt(t0, seed))
        eng.run_pending()


def test_legacy_engine_untouched_when_chunking_off(params, mesh1):
    """prefill_chunk=None keeps the one-shot path: the chunked program
    caches never grow, and the config knobs validate (a budget with
    nothing to schedule, or chunking in batch mode, is a hard error
    rather than silent misconfiguration)."""
    with assert_no_recompiles(_compiled_chunked_prefill,
                              _compiled_paged_chunked_prefill):
        eng = InferenceEngine(CFG, mesh1, params,
                              _config(prefill_chunk=None))
        h = eng.submit(_prompt(24))
        eng.run_pending()
        assert h.status == RequestStatus.COMPLETED
    assert eng.health()["prefill_chunk"] is None
    with pytest.raises(ValueError, match="tick_token_budget"):
        InferenceEngine(CFG, mesh1, params,
                        _config(prefill_chunk=None,
                                tick_token_budget=64))
    with pytest.raises(ValueError, match="continuous"):
        InferenceEngine(CFG, mesh1, params,
                        _config(mode="batch"))


# ---------------------------------------------------------------------------
# the named TPOT-stall regression
# ---------------------------------------------------------------------------

class _CallClock(ServingFaultInjector):
    """Injected clock: every compiled call (prefill, chunked prefill,
    decode chunk) advances time by exactly 1 — so flight-recorder
    timestamps measure schedule position, not this container's wall
    clock, and the stall bound is asserted deterministically."""

    def __init__(self):
        super().__init__()
        self.t = 0.0

    def on_decode_step(self, step, request_ids=()):
        self.t += 1.0
        super().on_decode_step(step, request_ids)


def test_tpot_stall_bounded_while_long_prompt_prefills(params, mesh1):
    """REGRESSION (ISSUE-10, by name): admitting a max-length prompt
    while 3 slots are mid-decode must NOT stall the residents for the
    prompt's full prefill. Under the token-budget scheduler every
    resident commits a decode chunk EVERY tick, and — on the injected
    compiled-call clock — no resident's inter-chunk gap exceeds
    ceil(tick_token_budget / prefill_chunk) prefill calls plus its own
    decode call. The one-shot counterpoint below shows the unbounded
    per-call prefill this replaces."""
    budget, pfc, dchunk = 12, 8, 2
    clk = _CallClock()
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=pfc, tick_token_budget=budget,
                decode_chunk=dchunk, max_new_tokens=16, num_slots=4),
        fault_injector=clk,
        recorder=FlightRecorder(clock=lambda: clk.t))
    residents = [eng.submit(_prompt(6, i), max_new_tokens=16)
                 for i in range(3)]
    eng.tick()                             # all 3 seated + first chunk
    long_req = eng.submit(_prompt(CFG.max_len - 16, 9),
                          max_new_tokens=4)
    while eng._is_prefilling(long_req):
        before = [r.generated.shape[0] for r in residents]
        chunks0 = eng.registry.get("serving_prefill_chunks").value
        eng.tick()
        # (a) every resident advanced by exactly one decode chunk
        for r, b in zip(residents, before):
            assert r.generated.shape[0] == min(b + dchunk, 16), \
                "resident stalled while the long prompt prefilled"
        # (b) the tick's prefill work respected the budget
        assert (eng.registry.get("serving_prefill_chunks").value
                - chunks0) <= -(-budget // pfc)
    eng.run_pending()
    # (c) the injected-clock gap bound over every resident's trace
    bound = -(-budget // pfc) + 1
    for r in residents:
        ts = [e.ts for e in r.trace.events
              if e.kind in ("prefill_done", "decode_chunk")]
        gaps = np.diff(ts)
        assert gaps.size and gaps.max() <= bound, \
            f"inter-token gap {gaps.max()} > {bound} compiled calls"
    # everyone token-exact despite the interleaving
    for i, r in enumerate(residents):
        np.testing.assert_array_equal(
            r.result(0), _solo(params, mesh1, _prompt(6, i), 16))
    np.testing.assert_array_equal(
        long_req.result(0),
        _solo(params, mesh1, _prompt(CFG.max_len - 16, 9), 4))

    # counterpoint: the one-shot engine runs the SAME admission as ONE
    # compiled prefill spanning the whole prompt — per-call prefill
    # work is bounded only by prompt length, which is the stall
    eng1 = InferenceEngine(CFG, mesh1, params,
                           _config(prefill_chunk=None,
                                   max_new_tokens=4, num_slots=4))
    eng1.submit(_prompt(CFG.max_len - 16, 9), max_new_tokens=4)
    eng1.tick()
    assert eng1.registry.get(
        "serving_prefill_seconds")._unlabeled().snapshot()[2] == 1


def test_prefill_is_oldest_first_for_ttft_fairness(params, mesh1):
    """Two long admissions share the prefill budget oldest-first: the
    earlier submission reaches its first token first (admission order
    == queue order — the _fill_slots micro-assert feeds this)."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=8, tick_token_budget=8, num_slots=4,
                max_new_tokens=2))
    a = eng.submit(_prompt(40, 1), max_new_tokens=2)
    b = eng.submit(_prompt(40, 2), max_new_tokens=2)
    while not a.done() or not b.done():
        eng.tick()
        if a.generated.shape[0] == 0:
            assert b.generated.shape[0] == 0, \
                "younger admission sampled before the older one"
    assert a.trace.first_ts("prefill_done") <= \
        b.trace.first_ts("prefill_done")


# ---------------------------------------------------------------------------
# mid-prefill forensics: poison / preempt / cancel / deadline
# ---------------------------------------------------------------------------

def test_mid_prefill_chunk_fault_transient_retries(params, mesh1):
    """The new prefill_chunk_fail_at knob: a transient chunk failure
    retries the SAME chunk (same step index) and the request completes
    token-exact — the retry event carries prefill=True."""
    inj = ServingFaultInjector(prefill_chunk_fail_at=[1])
    eng = InferenceEngine(CFG, mesh1, params, _config(prefill_chunk=8),
                          fault_injector=inj)
    h = eng.submit(_prompt(24))
    eng.run_pending()
    assert h.status == RequestStatus.COMPLETED
    assert inj.prefill_chunks_failed == 1
    assert eng.stats["retries"] == 1
    assert any(e.kind == "retry" and e.data.get("prefill")
               for e in h.trace.events)
    np.testing.assert_array_equal(
        h.result(0), _solo(params, mesh1, _prompt(24), 6))


def test_mid_prefill_poison_isolates_without_touching_decoders(
        params, mesh1):
    """A request POISONED while mid-prefill: its chunk calls fail and
    isolation quarantines it — but decode calls never contained it
    (PREFILLING slots are excluded from decode), so the co-resident
    decoding request completes byte-exact WITHOUT a single decode
    retry. Stronger isolation than one-shot mode, where admission and
    decode share the tick's fate."""
    inj = ServingFaultInjector()
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=4, tick_token_budget=6,
                max_new_tokens=12, max_retries=1, num_slots=4),
        fault_injector=inj)
    good = eng.submit(_prompt(6, 1), max_new_tokens=12)
    eng.tick()
    bad = eng.submit(_prompt(40, 2), max_new_tokens=4)
    inj.poison_requests.add(bad.rid)
    eng.run_pending()
    assert bad.status == RequestStatus.QUARANTINED
    with pytest.raises(RequestQuarantined):
        bad.result(0)
    assert good.status == RequestStatus.COMPLETED
    np.testing.assert_array_equal(
        good.result(0), _solo(params, mesh1, _prompt(6, 1), 12))
    # the poisoned request's trace shows the forensic chain
    kinds = bad.trace.kinds()
    assert "preempted" in kinds and "quarantined" in kinds
    # and no retry event ever landed on the healthy decoder
    assert not any(e.kind == "retry" for e in good.trace.events)


def test_mid_prefill_persistent_chunk_fault_recovers_solo(params,
                                                          mesh1):
    """prefill_chunk_fail_at persistent at every step: the pooled
    chunked prefill can never advance, but isolation's solo re-run
    uses the ONE-SHOT scratch prefill (a different call kind the knob
    does not target), so the request still completes token-exact —
    committed-prefix resume generalizes to prefill chunk boundaries."""
    inj = ServingFaultInjector(prefill_chunk_fail_at=range(1000),
                               persistent=True)
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=8, max_retries=1,
                breaker_failure_threshold=100),
        fault_injector=inj)
    h = eng.submit(_prompt(24))
    eng.run_pending()
    assert h.status == RequestStatus.COMPLETED
    assert inj.prefill_chunks_failed >= 1
    assert eng.stats["preempted"] == 1
    np.testing.assert_array_equal(
        h.result(0), _solo(params, mesh1, _prompt(24), 6))


def test_mid_prefill_cancel_frees_slot(params, mesh1):
    """engine.cancel() on a mid-prefill request sheds it typed at the
    next tick boundary, frees the slot, and the pool keeps serving."""
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=4, tick_token_budget=4, num_slots=2))
    h = eng.submit(_prompt(40, 3))
    eng.tick()
    assert eng._is_prefilling(h)
    assert eng.cancel(h)
    eng.run_pending()
    assert h.status == RequestStatus.SHED
    with pytest.raises(RequestCancelled):
        h.result(0)
    assert eng.health()["slots_occupied"] == 0
    nxt = eng.submit(_prompt(8, 4))
    eng.run_pending()
    assert nxt.status == RequestStatus.COMPLETED


def test_mid_prefill_deadline_shed_with_injected_clock(params, mesh1):
    """A deadline that expires MID-PREFILL (injected engine clock)
    sheds the request typed `DeadlineExceeded` before it ever samples
    a token; `on_deadline='partial'` completes it with its (empty)
    committed tokens instead."""
    t = {"now": 0.0}
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=4, tick_token_budget=4, num_slots=2),
        clock=lambda: t["now"])
    shed = eng.submit(_prompt(40, 1), deadline_s=5.0)
    part = eng.submit(_prompt(40, 2), deadline_s=5.0,
                      on_deadline="partial")
    eng.tick()
    assert eng._is_prefilling(shed)
    t["now"] = 10.0                        # both deadlines expire
    eng.run_pending()
    assert shed.status == RequestStatus.SHED
    with pytest.raises(DeadlineExceeded):
        shed.result(0)
    assert part.status == RequestStatus.COMPLETED
    assert part.generated.shape[0] == 0    # nothing committed yet
    assert eng.health()["slots_occupied"] == 0


def test_mid_prefill_reload_preempts_and_requeues(tmp_path, params,
                                                  mesh1):
    """Hot reload while a slot is mid-prefill: the request is
    preempted (requeued, nothing committed), resets its chunk
    progress, and completes under the NEW weights."""
    from deeplearning4j_tpu.util.checkpointing import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "w"), use_orbax=False)
    mgr.save_tree(params, 1)
    zeroed = jax.tree_util.tree_map(lambda a: a * 0, params)
    mgr.save_tree(zeroed, 2)
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=4, tick_token_budget=4, num_slots=2,
                max_new_tokens=4))
    h = eng.submit(_prompt(40, 5), max_new_tokens=4)
    eng.tick()
    assert eng._is_prefilling(h) and h.generated.shape[0] == 0
    assert eng.reload_weights(mgr, step=2) == 2
    assert h.status == RequestStatus.QUEUED
    assert eng.stats["preempted"] == 1
    eng.run_pending()
    assert h.status == RequestStatus.COMPLETED
    # the continuation ran under the zeroed weights
    ref = InferenceEngine(CFG, mesh1, zeroed,
                          _config(prefill_chunk=None,
                                  max_new_tokens=4))
    s = ref.submit(_prompt(40, 5), max_new_tokens=4)
    ref.run_pending()
    np.testing.assert_array_equal(h.result(0), s.result(0))


def test_spec_decode_with_chunked_prefill_token_exact(params, mesh1):
    """Speculative decode composes with chunked prefill: PREFILLING
    slots are excluded from spec rounds (they are not decoding yet),
    a slot joins speculation the tick after its first token, and the
    self-drafting spec engine stays token-exact vs the plain chunked
    engine while a long admission prefills mid-pool."""
    kw = dict(prefill_chunk=4, tick_token_budget=6, num_slots=4,
              max_new_tokens=10)

    def run(spec: bool):
        extra = dict(spec_decode=True, draft="self") if spec else {}
        eng = InferenceEngine(CFG, mesh1, params,
                              _config(**kw, **extra))
        a = eng.submit(_prompt(6, 1), max_new_tokens=10)
        eng.tick()                         # a decoding
        b = eng.submit(_prompt(30, 2), max_new_tokens=4)
        eng.run_pending()                  # b prefills mid-pool
        return eng, a, b

    _, a_ref, b_ref = run(False)
    eng, a, b = run(True)
    np.testing.assert_array_equal(a.result(0), a_ref.result(0))
    np.testing.assert_array_equal(b.result(0), b_ref.result(0))
    assert eng.registry.get("serving_spec_drafted_tokens").value > 0


def test_fleet_failover_mid_prefill_resumes_on_survivor(params, mesh1):
    """A replica killed while its resident is MID-PREFILL: the router
    fails the request over to the survivor, which re-prefills from
    the committed prefix (nothing committed yet = full re-prefill)
    and completes token-exact vs an uninterrupted run — the
    committed-prefix resume contract generalizes to prefill chunk
    boundaries."""
    from deeplearning4j_tpu.parallel.failure import FleetFaultInjector
    from deeplearning4j_tpu.serving import FleetConfig, Router
    ec = _config(prefill_chunk=4, tick_token_budget=4, num_slots=2,
                 max_new_tokens=4)
    want = _solo(params, mesh1, _prompt(40, 3), 4)
    inj = FleetFaultInjector(kill_at={2: 0})   # mid-prefill: prompt 40
    #                                            at 4 tokens/tick
    r = Router(cfg=CFG, mesh=mesh1, params=params, num_replicas=2,
               engine_config=ec, fault_injector=inj,
               config=FleetConfig(restart_backoff_base_s=0.01))
    try:
        h = r.submit(_prompt(40, 3), max_new_tokens=4)
        r.run_pending()
        assert inj.kills_injected == 1
        assert h.status == RequestStatus.COMPLETED
        np.testing.assert_array_equal(h.result(0), want)
    finally:
        r.close()


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------

def test_chunked_metrics_events_and_debugz(params, mesh1):
    """serving_prefill_chunks_total + serving_tick_budget_utilization
    publish and render; admitted/prefill_done/decode_chunk events
    carry the prefill_chunk field; debugz grows the chunked_prefill
    section and per-slot PREFILLING phase."""
    from deeplearning4j_tpu.observability.export import prometheus_text
    eng = InferenceEngine(
        CFG, mesh1, params,
        _config(prefill_chunk=8, tick_token_budget=10,
                max_new_tokens=4, num_slots=2))
    h = eng.submit(_prompt(24, 1))
    eng.tick()
    dz = eng.debugz()
    if dz["slots"]:                        # still mid-prefill
        assert dz["slots"][0]["phase"] in ("prefilling", "decoding")
    # sample the utilization gauge MID-traffic: it reads the last
    # tick's spend, and the (default-pipelined) loop ends on an empty
    # commit-only tick
    util_seen = 0.0
    for _ in range(256):
        if not eng.tick():
            break
        util_seen = max(util_seen, eng.registry.get(
            "serving_tick_budget_utilization").value)
    eng.run_pending()
    # prompt 24 @ budget 10/tick: chunks 8+2 | 8+2 | 4 = 5 calls
    assert eng.registry.get("serving_prefill_chunks").value == 5
    assert util_seen > 0
    text = prometheus_text(eng.registry)
    assert "serving_prefill_chunks_total 5" in text
    assert "serving_tick_budget_utilization" in text
    ev = {e.kind: e for e in h.trace.events}
    assert ev["admitted"].data["prefill_chunk"] == 8
    assert ev["prefill_done"].data["prefill_chunk"] == 8
    assert "prefill_chunk" in ev["decode_chunk"].data
    dz = eng.debugz()["chunked_prefill"]
    assert dz["prefill_chunk"] == 8
    assert dz["tick_token_budget"] == 10
    assert dz["prefill_chunks_total"] == 5


def test_injector_on_prefill_chunk_semantics():
    inj = ServingFaultInjector(prefill_chunk_fail_at=[0],
                               prefill_fail_at=[1],
                               poison_requests=[7])
    with pytest.raises(TrainingFailure, match="prefill-chunk"):
        inj.on_prefill_chunk(0)            # chunk-only knob
    inj.on_prefill_chunk(0)                # one-shot: consumed
    with pytest.raises(TrainingFailure, match="prefill"):
        inj.on_prefill_chunk(1)            # prefill_fail_at fires too
    with pytest.raises(TrainingFailure, match="poisoned"):
        inj.on_prefill_chunk(2, request_ids=[7])
    inj.on_prefill_chunk(2, request_ids=[3])
    assert inj.prefill_chunks_failed == 1
    assert inj.prefills_failed == 1
