"""Keras-backend server, streaming routes, zoo configs, legacy listeners.

Models the reference's small-module surfaces (deeplearning4j-keras py4j
entry point, dl4j-streaming Camel routes, deeplearning4j-ui legacy
listeners).
"""
import json
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.keras_server import (DeepLearning4jEntryPoint,
                                             KerasServer)
from deeplearning4j_tpu.streaming import (DL4jServeRoute, NDArrayConsumer,
                                          NDArrayPublisher)


def _write_keras_fixture(path):
    import h5py
    rng = np.random.default_rng(0)
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    w2 = rng.normal(size=(8, 2)).astype(np.float32)
    mc = {"class_name": "Sequential", "config": [
        {"class_name": "Dense", "config": {"name": "d1", "units": 8,
         "activation": "relu", "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {"name": "d2", "units": 2,
         "activation": "softmax"}}]}
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(mc).encode()
        f.attrs["training_config"] = json.dumps(
            {"loss": "categorical_crossentropy"}).encode()
        g = f.create_group("model_weights")
        g.attrs["layer_names"] = np.array([b"d1", b"d2"], dtype="S8")
        for n, w in (("d1", w1), ("d2", w2)):
            lg = g.create_group(n)
            lg.attrs["weight_names"] = np.array(
                [f"{n}/kernel:0".encode(), f"{n}/bias:0".encode()],
                dtype="S32")
            lg.create_dataset(f"{n}/kernel:0", data=w)
            lg.create_dataset(f"{n}/bias:0",
                              data=np.zeros(w.shape[1], np.float32))


def test_entry_point_fit_and_predict(tmp_path):
    model_path = str(tmp_path / "m.h5")
    _write_keras_fixture(model_path)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
    data_path = str(tmp_path / "d.npz")
    np.savez(data_path, features=x, labels=y)

    ep = DeepLearning4jEntryPoint()
    res = ep.fit(model_path, data_path, epochs=2, batch_size=16)
    assert len(res["scores"]) == 2
    assert all(np.isfinite(s) for s in res["scores"])
    pred = ep.predict(model_path, data_path)
    out = np.load(pred["output_path"])
    assert out.shape == (32, 2)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-5)


def test_keras_server_http_roundtrip(tmp_path):
    model_path = str(tmp_path / "m.h5")
    _write_keras_fixture(model_path)
    x = np.random.default_rng(2).normal(size=(8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[[0, 1] * 4]
    data_path = str(tmp_path / "d.npz")
    np.savez(data_path, features=x, labels=y)

    server = KerasServer(port=0)
    try:
        def post(path, payload):
            req = urllib.request.Request(
                server.url + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        with urllib.request.urlopen(server.url + "/health",
                                    timeout=5) as r:
            assert json.loads(r.read()) == {"ok": True}
        res = post("/fit", {"model_path": model_path,
                            "data_path": data_path, "epochs": 1})
        assert "scores" in res and len(res["scores"]) == 1
        res = post("/predict", {"model_path": model_path,
                                "data_path": data_path})
        assert np.load(res["output_path"]).shape == (8, 2)
    finally:
        server.stop()


def test_streaming_serve_route():
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = NeuralNetConfiguration(seed=1).list(
        DenseLayer(n_in=3, n_out=4, activation="tanh"),
        OutputLayer(n_out=2, activation="softmax",
                    loss_function="mcxent"))
    net = MultiLayerNetwork(conf).init()

    route = DL4jServeRoute(net, "in_topic", "out_topic")
    route.start()
    try:
        pub = NDArrayPublisher("in_topic")
        sub = NDArrayConsumer("out_topic")
        x = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
        pub.publish(x)
        out = sub.consume(timeout=30)
        assert out.shape == (5, 2)
        np.testing.assert_allclose(out.sum(1), 1.0, atol=1e-5)
    finally:
        route.stop()


def test_zoo_char_rnn_and_mlp_train():
    from deeplearning4j_tpu.models.zoo import char_rnn_lstm, mlp_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = char_rnn_lstm(vocab_size=12, hidden=16, layers=2,
                         tbptt_length=8)
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = np.eye(12, dtype=np.float32)[rng.integers(0, 12, (4, 16))]
    y = np.eye(12, dtype=np.float32)[rng.integers(0, 12, (4, 16))]
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    out = net.output(x)
    assert out.shape == (4, 16, 12)

    mlp = MultiLayerNetwork(mlp_mnist(hidden=32)).init()
    xb = rng.normal(size=(8, 784)).astype(np.float32)
    yb = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 8)]
    mlp.fit(xb, yb)
    assert np.isfinite(mlp.score_value)


def test_legacy_listeners(tmp_path):
    from deeplearning4j_tpu.models.zoo import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.ui.legacy import (ConvolutionalIterationListener,
                                              FlowIterationListener)
    net = MultiLayerNetwork(lenet_mnist()).init()
    conv_l = ConvolutionalIterationListener(str(tmp_path / "acts"),
                                            frequency=1)
    flow_l = FlowIterationListener(str(tmp_path / "flow.json"), frequency=1)
    net.set_listeners(conv_l, flow_l)
    rng = np.random.default_rng(0)
    x = rng.random((4, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
    conv_l.record_input(x)
    net.fit(x, y)
    acts = list((tmp_path / "acts").glob("*.npy"))
    assert acts, "no activation grids saved"
    grid = np.load(acts[0])
    assert grid.ndim == 3  # [C, H, W]
    flow = json.load(open(tmp_path / "flow.json"))
    assert len(flow["layers"]) == 6
    assert flow["layers"][1]["inputs"] == [flow["layers"][0]["name"]]
