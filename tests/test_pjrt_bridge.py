"""C++ PJRT bridge tests against the hermetic stub plugin.

The stub (native/pjrt_stub_plugin.cpp) is the CI stand-in for
libtpu.so behind the identical PJRT C ABI — the reference's
"same tests, different backend" pattern (SURVEY §4: nd4j-native
profile standing in for CUDA; CuDNNGradientChecks validating the fast
path against the baseline). These tests exercise the full bridge
surface: plugin load, client + device enumeration, MLIR compile,
H2D/D2H, execute, error paths, and buffer lifecycle.
"""
import numpy as np
import pytest

from deeplearning4j_tpu import pjrt

_STABLEHLO_ADD = """
module @jit_add {
  func.func public @main(%arg0: tensor<8xf32>, %arg1: tensor<8xf32>) -> tensor<8xf32> {
    %0 = stablehlo.add %arg0, %arg1 : tensor<8xf32>
    return %0 : tensor<8xf32>
  }
}
"""

_STABLEHLO_MUL = """
module @jit_mul {
  func.func public @main(%arg0: tensor<2x3xf32>, %arg1: tensor<2x3xf32>) -> tensor<2x3xf32> {
    %0 = stablehlo.multiply %arg0, %arg1 : tensor<2x3xf32>
    return %0 : tensor<2x3xf32>
  }
}
"""


@pytest.fixture(scope="module")
def runtime():
    if pjrt.get_bridge() is None:
        pytest.skip("native toolchain unavailable")
    stub = pjrt.stub_plugin_path()
    if stub is None:
        pytest.skip("stub plugin build failed")
    rt = pjrt.PjrtRuntime(plugin_path=stub)
    yield rt
    rt.close()


def test_plugin_load_and_client(runtime):
    major, minor = runtime.api_version
    assert major == 0 and minor > 0
    assert runtime.platform_name == "dl4j_stub"
    assert runtime.device_count == 1


def test_h2d_d2h_roundtrip(runtime):
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    buf = runtime.to_device(x)
    assert buf.nbytes == x.nbytes
    back = buf.to_numpy()
    assert back.dtype == np.float32 and back.shape == (4, 6)
    np.testing.assert_array_equal(back, x)
    buf.close()


def test_compile_and_execute_add(runtime):
    exe = runtime.compile(_STABLEHLO_ADD)
    assert exe.num_outputs == 1
    a = np.linspace(0, 1, 8).astype(np.float32)
    b = np.linspace(1, 2, 8).astype(np.float32)
    (out,) = exe(a, b)
    np.testing.assert_allclose(out, a + b, rtol=1e-6)
    exe.close()


def test_compile_and_execute_multiply_2d(runtime):
    exe = runtime.compile(_STABLEHLO_MUL)
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.full((2, 3), 3.0, np.float32)
    (out,) = exe(a, b)
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out, a * b)
    exe.close()


def test_compile_error_surfaces_plugin_message(runtime):
    with pytest.raises(pjrt.PjrtError) as ei:
        runtime.compile("module @nope { }")
    assert "stablehlo" in str(ei.value)


def test_execute_wrong_arity_errors(runtime):
    exe = runtime.compile(_STABLEHLO_ADD)
    a = runtime.to_device(np.zeros(8, np.float32))
    with pytest.raises(pjrt.PjrtError):
        exe.execute([a])
    a.close()
    exe.close()


def test_missing_plugin_path_errors():
    if pjrt.get_bridge() is None:
        pytest.skip("native toolchain unavailable")
    with pytest.raises(pjrt.PjrtError) as ei:
        pjrt.PjrtRuntime(plugin_path="/nonexistent/libfoo.so")
    assert "plugin load failed" in str(ei.value)


def test_jax_lowering_feeds_the_bridge(runtime):
    """The intended production flow: jax traces/lowers a framework
    model step to StableHLO text; the native runtime compiles and runs
    it. The stub only knows single-op add, which jax emits for this
    function."""
    import jax
    import jax.numpy as jnp

    def f(x, y):
        return jnp.add(x, y)

    lowered = jax.jit(f).lower(jnp.zeros(8, jnp.float32),
                               jnp.zeros(8, jnp.float32))
    mlir_text = lowered.compiler_ir("stablehlo")
    exe = runtime.compile(str(mlir_text))
    a = np.ones(8, np.float32)
    (out,) = exe(a, a)
    np.testing.assert_allclose(out, 2 * np.ones(8, np.float32))
    exe.close()


def test_executable_cache_hit_and_miss(runtime):
    """Shape-keyed native executable cache (SURVEY §7: 'executable
    caching keyed on shapes')."""
    e1 = runtime.compile_cached(_STABLEHLO_ADD, key="add:8xf32")
    assert not e1.cache_hit
    assert runtime.exec_cache_size == 1
    e2 = runtime.compile_cached(_STABLEHLO_ADD, key="add:8xf32")
    assert e2.cache_hit
    assert runtime.exec_cache_size == 1
    e3 = runtime.compile_cached(_STABLEHLO_MUL, key="mul:2x3xf32")
    assert not e3.cache_hit
    assert runtime.exec_cache_size == 2
    a = np.arange(8, dtype=np.float32)
    (out,) = e2(a, a)
    np.testing.assert_allclose(out, a + a)
    # cached handles are cache-owned: close() must be a safe no-op
    e1.close()
    e4 = runtime.compile_cached(_STABLEHLO_ADD, key="add:8xf32")
    assert e4.cache_hit
    (out2,) = e4(a, a)
    np.testing.assert_allclose(out2, a + a)


def test_async_executor_fifo(runtime):
    """Native dispatch queue: submit N executions, wait out of order."""
    exe = runtime.compile(_STABLEHLO_ADD)
    with runtime.async_executor() as ex:
        bufs = []
        tickets = []
        for i in range(4):
            a = np.full(8, float(i), np.float32)
            b1, b2 = runtime.to_device(a), runtime.to_device(a)
            bufs += [b1, b2]
            tickets.append(ex.submit(exe, [b1, b2]))
        # wait in reverse order: results must match their own ticket
        for i in reversed(range(4)):
            (out,) = ex.wait(tickets[i])
            np.testing.assert_allclose(out.to_numpy(),
                                       np.full(8, 2.0 * i, np.float32))
            out.close()
        for b in bufs:
            b.close()
    exe.close()


def test_async_executor_error_path(runtime):
    """Wrong operand arity is rejected SYNCHRONOUSLY at submit (the r4
    guard — a mismatched execute crashed the axon terminal's backend
    connection instead of erroring, benchmarks/bridge_bisect.py), and
    a failing NATIVE execution still surfaces its error at wait()
    without poisoning the queue (covered by disabling the Python-side
    guard, as happens for bytecode modules whose arity can't be
    parsed)."""
    exe = runtime.compile(_STABLEHLO_ADD)
    assert exe._expected_args == 2
    b = runtime.to_device(np.arange(8, dtype=np.float32))
    with runtime.async_executor() as ex:
        with pytest.raises(pjrt.PjrtError, match="takes 2 operands"):
            ex.submit(exe, [b])            # wrong arity: sync reject
        exe._expected_args = None          # unparsable-arity scenario
        bad = ex.submit(exe, [b])          # reaches the native path
        good_b2 = runtime.to_device(np.arange(8, dtype=np.float32))
        good = ex.submit(exe, [b, good_b2])
        with pytest.raises(pjrt.PjrtError):
            ex.wait(bad)
        (out,) = ex.wait(good)
        np.testing.assert_allclose(out.to_numpy(),
                                   2 * np.arange(8, dtype=np.float32))
        out.close()
        good_b2.close()
    b.close()
    exe.close()


def test_client_create_options_marshalling():
    """PJRT_NamedValue create_options through the C ABI (string, int64
    and bool kinds) — the path real plugins (libtpu/axon) require for
    session/topology options; the stub accepts and ignores them, so
    this pins the marshalling itself (round-3: the real-chip proof in
    benchmarks/pjrt_chip_proof.py drives the same path end-to-end)."""
    stub = pjrt.stub_plugin_path()
    if stub is None:
        pytest.skip("stub plugin build unavailable")
    rt = pjrt.PjrtRuntime(plugin_path=stub, create_options={
        "topology": "v5e:1x1x1",     # kString
        "n_slices": 1,               # kInt64
        "remote_compile": False,     # kBool
        "session_id": "test-session",
    })
    try:
        assert rt.device_count >= 1
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_array_equal(rt.to_device(x).to_numpy(), x)
    finally:
        rt.close()


def test_h2d_d2h_rank3_and_rank4_roundtrip(runtime):
    """Rank>=3 layout regression (round-3: the real plugin's default
    layout for rank>=3 is a permuted order — the bridge now pins
    C-order on both directions; on the real chip this corrupted every
    conv weight before the fix)."""
    for shape in [(2, 3, 4), (2, 3, 4, 5), (5, 5, 1, 20)]:
        x = (np.arange(np.prod(shape), dtype=np.float32)
             .reshape(shape) + 1.5)
        buf = runtime.to_device(x)
        np.testing.assert_array_equal(buf.to_numpy(), x)
        buf.close()
