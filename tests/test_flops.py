"""FLOPs accounting / MFU reporting (util/flops.py).

The reference has no FLOPs accounting (PerformanceListener.java reports
examples/sec only); MFU is this framework's honest cross-round metric,
so its plumbing gets its own tests.
"""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util.flops import (chip_peak_flops, cost_analysis,
                                           mfu, program_flops)


def test_matmul_flops_exact():
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.zeros((128, 128), jnp.float32)
    flops = program_flops(f, a, a)
    if flops is None:  # backend without a cost model: nothing to check
        return
    assert flops == 2 * 128 ** 3


def test_cost_analysis_returns_dict():
    f = jax.jit(lambda a: jnp.sin(a).sum())
    ca = cost_analysis(f, jnp.zeros((16,), jnp.float32))
    assert isinstance(ca, dict)


def test_peak_and_mfu_unknown_on_cpu():
    # the suite runs on the virtual CPU mesh: no peak table entry
    assert chip_peak_flops(jax.devices()[0]) is None
    assert mfu(1e12, 1.0, jax.devices()[0]) is None
    assert mfu(None, 1.0) is None


def test_fit_batched_cost_smoke():
    """fit_batched_cost lowers the real scanned program and leaves the
    network untouched (no execution, no donation)."""
    from deeplearning4j_tpu.models.zoo import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(lenet_mnist()).init()
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.random((1, 8, 784), dtype=np.float32))
    ys = jax.nn.one_hot(jnp.asarray(rng.integers(0, 10, (1, 8))), 10)
    before = jax.tree_util.tree_leaves(net.params)[0]
    ca = net.fit_batched_cost(xs, ys, epochs=2)
    assert isinstance(ca, dict)
    after = jax.tree_util.tree_leaves(net.params)[0]
    assert before is after  # params untouched, buffers not donated
    # the program must still run after costing (cache reuse is safe)
    scores = net.fit_batched(xs, ys, epochs=2)
    assert scores.shape == (2,)
