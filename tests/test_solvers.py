"""Second-order solver tests (reference: optimize/solvers/ — Solver
dispatch on OptimizationAlgorithm, BackTrackLineSearch, terminations).

Mirrors the reference's solver test style (deeplearning4j-core
src/test .../optimize/solver/TestOptimizers.java: each algorithm must
drive the score down on a small problem and on a tiny net).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.train.solvers import (LBFGS, ConjugateGradient,
                                              EpsTermination,
                                              LineGradientDescent,
                                              Norm2Termination,
                                              StochasticGradientDescent,
                                              backtrack_line_search)


def _quadratic():
    """f(w) = 0.5 wᵀ A w - bᵀ w, A spd — unique minimum at A⁻¹ b."""
    rng = np.random.default_rng(0)
    M = rng.standard_normal((6, 6))
    A = jnp.asarray(M @ M.T + 6 * np.eye(6))
    b = jnp.asarray(rng.standard_normal(6))

    def f(w):
        return 0.5 * w @ A @ w - b @ w

    w_star = jnp.linalg.solve(A, b)
    return jax.value_and_grad(f), w_star, f


def _rosenbrock_vg():
    def f(w):
        return jnp.sum(100.0 * (w[1:] - w[:-1] ** 2) ** 2
                       + (1.0 - w[:-1]) ** 2)
    return jax.value_and_grad(f)


@pytest.mark.parametrize("cls,iters", [(LBFGS, 30),
                                       (ConjugateGradient, 40),
                                       (LineGradientDescent, 120)])
def test_solver_minimizes_quadratic(cls, iters):
    vg, w_star, f = _quadratic()
    w0 = jnp.zeros(6)
    solver = cls(vg, max_iterations=iters,
                 terminations=[Norm2Termination(1e-8)])
    w, score = solver.optimize(w0)
    assert float(f(w)) <= float(f(w0))
    np.testing.assert_allclose(np.asarray(w), np.asarray(w_star),
                               atol=2e-2)


def test_lbfgs_beats_gradient_descent_on_rosenbrock():
    vg = _rosenbrock_vg()
    w0 = jnp.zeros(4)
    lw, lscore = LBFGS(vg, max_iterations=80,
                       terminations=[EpsTermination(1e-14, 1e-12)]
                       ).optimize(w0)
    gw, gscore = LineGradientDescent(vg, max_iterations=80).optimize(w0)
    assert lscore < float(vg(w0)[0])
    assert lscore <= gscore + 1e-6


def test_sgd_solver_descends():
    vg, _, f = _quadratic()
    w0 = jnp.zeros(6)
    solver = StochasticGradientDescent(vg, max_iterations=20,
                                       learning_rate=0.05)
    w, score = solver.optimize(w0)
    assert score < float(f(w0))


def test_backtrack_line_search_armijo():
    def f(w):
        return float(jnp.sum(w * w))
    w = jnp.ones(3)
    grad = 2.0 * w
    step, new_w, new_score = backtrack_line_search(f, w, f(w), grad, -grad)
    assert step > 0.0
    assert new_score < f(w)
    # uphill direction: refuses to move
    step, new_w, new_score = backtrack_line_search(f, w, f(w), grad, grad)
    assert step == 0.0


def test_score_history_monotone_nonincreasing():
    vg, _, _ = _quadratic()
    solver = LBFGS(vg, max_iterations=15)
    solver.optimize(jnp.zeros(6))
    h = solver.score_history
    assert len(h) >= 2
    assert all(h[i + 1] <= h[i] + 1e-9 for i in range(len(h) - 1))


def test_network_fit_with_lbfgs_and_cg():
    """Solver dispatch from MultiLayerNetwork.fit (reference:
    Solver.java:48): second-order algos must reduce the net's score."""
    from deeplearning4j_tpu.nn.conf.configuration import (
        NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    rng = np.random.default_rng(7)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    labels = (x.sum(axis=1) > 0).astype(np.int64)
    y = np.eye(3)[np.minimum(labels * 2, 2)].astype(np.float32)

    for algo in ("lbfgs", "conjugate_gradient", "line_gradient_descent"):
        conf = (NeuralNetConfiguration(
                    seed=12, optimization_algo=algo, num_iterations=8)
                .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent")))
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(conf).init()
        before = net.score(x, y)
        net.fit(x, y)
        after = float(net.score_value)
        assert after < before, f"{algo}: {after} !< {before}"


def test_lbfgs_on_computation_graph():
    """Second-order solvers drive ComputationGraph too (reference:
    ComputationGraph training dispatches through Solver.java like
    MultiLayerNetwork)."""
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph.computation_graph import \
        ComputationGraph
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer

    rng = np.random.default_rng(2)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    labels = (x.sum(axis=1) > 0).astype(np.int64)
    y = np.eye(3, dtype=np.float32)[np.minimum(labels * 2, 2)]
    conf = (NeuralNetConfiguration(seed=1, optimization_algo="lbfgs",
                                   num_iterations=20, activation="tanh")
            .graph_builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_in=4, n_out=12), "in")
            .add_layer("out", OutputLayer(n_in=12, n_out=3,
                                          activation="softmax",
                                          loss_function="mcxent"), "h")
            .set_outputs("out")
            .build())
    g = ComputationGraph(conf).init()
    s0 = g.score(x, y)
    g.fit(x, y)
    s1 = g.score(x, y)
    assert s1 < s0 * 0.7, (s0, s1)
    assert g.iteration_count > 1  # per-internal-step listener advances


def test_lr_policies_torchstep_and_score():
    """reference: LearningRatePolicy TorchStep (periodic multiply) and
    Score (host-side plateau decay)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train.updaters import (apply_score_decay,
                                                   compute_learning_rate)

    conf = NeuralNetConfiguration(seed=1, learning_rate=0.1,
                                  lr_policy="torchstep",
                                  lr_policy_decay_rate=0.5,
                                  lr_policy_steps=10).list(
        DenseLayer(n_in=4, n_out=4),
        OutputLayer(n_out=2, activation="softmax"))
    tc = conf.training
    assert float(compute_learning_rate(tc, 0)) == pytest.approx(0.1)
    assert float(compute_learning_rate(tc, 10)) == pytest.approx(0.05)
    assert float(compute_learning_rate(tc, 25)) == pytest.approx(0.025)

    sconf = NeuralNetConfiguration(seed=1, learning_rate=0.1,
                                   lr_policy="score",
                                   lr_policy_decay_rate=0.5).list(
        DenseLayer(n_in=4, n_out=4),
        OutputLayer(n_out=2, activation="softmax"))
    net = MultiLayerNetwork(sconf).init()
    assert float(compute_learning_rate(net.conf.training, 7)) \
        == pytest.approx(0.1)
    assert not apply_score_decay(net, previous_score=1.0,
                                 current_score=0.9)  # improving: no decay
    assert apply_score_decay(net, previous_score=0.9, current_score=0.95)
    assert net.conf.training.learning_rate == pytest.approx(0.05)
    assert float(compute_learning_rate(net.conf.training, 7)) \
        == pytest.approx(0.05)
    # per-layer baked LRs scale with the base: multipliers must NOT
    # cancel the decay (effective per-layer lr == decayed base)
    mults = net._lr_multipliers()
    for name, m in mults.items():
        assert m == pytest.approx(1.0), (name, m)
    # net still trains after the cache invalidation
    import numpy as np
    x = np.random.default_rng(0).random((8, 4), np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(
        0, 2, 8)]
    net.fit(x, y)
    assert np.isfinite(float(net.score_value))
