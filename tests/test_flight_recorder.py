"""Flight recorder + SLO layer + timeline export (ISSUE-6 suite).

The tentpole guarantees, each proven deterministically on the CPU
backend:

- a served request's `RequestHandle.trace` is a COMPLETE typed
  lifecycle record (submit → queued → admitted{slot,bucket} →
  prefill_done → decode_chunk{tokens}* → finished) in both scheduling
  modes, with monotone timestamps;
- fault injection leaves forensic traces: a poisoned request's trace
  reads retry → … → quarantined, while co-resident survivors read
  preempted → re-admitted (scratch) → finished; reload preemption
  reads preempted{reason=reload} → re-admitted;
- the SLO layer derives TTFT / TPOT / e2e / queue-age / goodput from
  the traces (exact values under an injected clock) and publishes
  registry histograms + a windowed report() — TTFT and queue-age in
  BATCH mode too, not just continuous;
- `/timeline.json` parses as valid Chrome trace_event JSON with one
  lane per slot plus a queue lane; `/debugz` and `/slo` serve the live
  introspection dicts;
- NULL_RECORDER / NULL_REGISTRY disable everything by injection with
  identical decode results.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params)
from deeplearning4j_tpu.observability import (FlightRecorder,
                                              MetricsRegistry,
                                              MetricsServer,
                                              NULL_RECORDER,
                                              NULL_TRACE, SLOTracker,
                                              prometheus_text,
                                              timeline_json)
from deeplearning4j_tpu.parallel.failure import ServingFaultInjector
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.serving import (EngineConfig, InferenceEngine,
                                        RequestStatus)

CFG = TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                        n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(MeshSpec(data=1, model=1))


def _prompt(t0=8, seed=0):
    return (np.arange(t0, dtype=np.int32) * (seed + 3)) % CFG.vocab_size


def _config(**kw):
    base = dict(decode_chunk=2, max_new_tokens=6, backoff_base_s=0.0)
    base.update(kw)
    return EngineConfig(**base)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


# ---------------------------------------------------------------------------
# trace completeness
# ---------------------------------------------------------------------------

def test_trace_complete_lifecycle_continuous(params, mesh1):
    """Happy path, continuous mode: the exact event sequence with the
    typed payloads — slot + bucket on admission, one prefill_done
    carrying the first token, ~budget/chunk decode_chunk events, a
    finished terminal — and non-decreasing monotonic timestamps."""
    eng = InferenceEngine(CFG, mesh1, params, _config())
    h = eng.submit(_prompt())
    assert h.trace.kinds() == ["submit", "queued"]
    eng.run_pending()
    assert h.status == RequestStatus.COMPLETED
    kinds = h.trace.kinds()
    assert kinds[:3] == ["submit", "queued", "admitted"]
    assert kinds[3] == "prefill_done"
    assert kinds[-1] == "finished" and h.trace.complete()
    # 6 new tokens at chunk 2: 1 from prefill + 3 chunks (last partial)
    assert kinds.count("decode_chunk") == 3
    evs = h.trace.events
    by_kind = {e.kind: e for e in evs}
    assert by_kind["submit"].data["prompt_tokens"] == 8
    assert by_kind["submit"].data["max_new_tokens"] == 6
    assert by_kind["admitted"].data["slot"] == 0
    assert by_kind["admitted"].data["bucket"] == 16   # 8 rounds up
    assert by_kind["prefill_done"].data["tokens"] == 1
    assert by_kind["finished"].data["tokens"] == 6
    assert not by_kind["finished"].data["partial"]
    ts = [e.ts for e in evs]
    assert ts == sorted(ts)
    # the engine ring saw the same request's events
    assert [e.kind for e in eng.recorder.recent(rid=h.rid)] == kinds


def test_trace_and_ttft_in_batch_mode(params, mesh1):
    """Batch mode (ISSUE-6 satellite): the trace is complete there
    too, and the first decode chunk IS the first-token moment — so
    serving_ttft_seconds and serving_queue_age_seconds get observed
    in BOTH modes, not just continuous."""
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(mode="batch", decode_chunk=2))
    h = eng.submit(_prompt())
    eng.run_pending()
    assert h.status == RequestStatus.COMPLETED
    kinds = h.trace.kinds()
    assert kinds[:3] == ["submit", "queued", "admitted"]
    assert h.trace.events[2].data == {"batch_size": 1}
    assert kinds.count("decode_chunk") == 3           # 6 tokens / 2
    assert kinds[-1] == "finished"
    for name in ("serving_ttft_seconds", "serving_queue_age_seconds",
                 "serving_e2e_seconds"):
        hist = eng.registry.get(name)
        assert hist is not None, name
        assert hist._unlabeled().snapshot()[2] == 1, name
    # TPOT defined (6 tokens across 3 chunk events)
    assert eng.registry.get(
        "serving_tpot_seconds")._unlabeled().snapshot()[2] == 1


def test_deadline_shed_trace_and_slo_outcome(params, mesh1):
    """An already-expired request sheds at admission: trace ends
    shed{reason=deadline}, and the SLO window books the outcome (so
    goodput < 1)."""
    eng = InferenceEngine(CFG, mesh1, params, _config())
    ok = eng.submit(_prompt(8, 1))
    doomed = eng.submit(_prompt(8, 2), deadline_s=-0.001)
    eng.run_pending()
    assert ok.status == RequestStatus.COMPLETED
    assert doomed.status == RequestStatus.SHED
    assert doomed.trace.kinds() == ["submit", "queued", "shed"]
    assert doomed.trace.events[-1].data["reason"] == "deadline"
    rep = eng.slo_report()
    assert rep["window"] == 2
    assert rep["outcomes"] == {"ok": 1, "late": 0, "shed": 1,
                               "quarantined": 0}
    assert rep["goodput"] == 0.5
    assert eng.registry.get("serving_goodput_ratio").value == 0.5


# ---------------------------------------------------------------------------
# satellite: fault-injection forensics
# ---------------------------------------------------------------------------

def test_quarantine_and_survivor_traces_under_poison(params, mesh1):
    """ServingFaultInjector poison in a 3-resident pool: the
    quarantined request's trace contains retry → quarantined (in that
    order), and each co-resident survivor's trace contains preempted →
    re-admitted (on the scratch pool) → finished."""
    inj = ServingFaultInjector()
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(max_retries=1), fault_injector=inj)
    a = eng.submit(_prompt(8, 1))
    bad = eng.submit(_prompt(12, 2))
    b = eng.submit(_prompt(10, 3))
    inj.poison_requests.add(bad.rid)
    eng.run_pending()

    assert bad.status == RequestStatus.QUARANTINED
    kinds = bad.trace.kinds()
    assert "retry" in kinds and kinds[-1] == "quarantined"
    assert kinds.index("retry") < kinds.index("quarantined")
    # poisoned request was evicted from the pool before its solo run
    assert "preempted" in kinds

    for surv in (a, b):
        kinds = surv.trace.kinds()
        assert surv.status == RequestStatus.COMPLETED
        i_pre = kinds.index("preempted")
        readmits = [j for j, k in enumerate(kinds)
                    if k == "admitted" and j > i_pre]
        assert readmits, f"no re-admission after preemption: {kinds}"
        ev = surv.trace.events[readmits[0]]
        assert ev.data.get("scratch") is True      # solo scratch pool
        assert kinds[-1] == "finished"
        assert surv.trace.events[i_pre].data["reason"] == "isolation"


def test_prefill_fault_retry_is_traced(params, mesh1):
    """A transient admission-prefill fault leaves a retry event with
    prefill=True on every request seated in that admission round."""
    inj = ServingFaultInjector(prefill_fail_at=[0])
    eng = InferenceEngine(CFG, mesh1, params, _config(),
                          fault_injector=inj)
    h = eng.submit(_prompt())
    eng.run_pending()
    assert h.status == RequestStatus.COMPLETED
    retries = [e for e in h.trace.events if e.kind == "retry"]
    assert len(retries) == 1
    assert retries[0].data["prefill"] is True
    assert retries[0].data["step"] == 0


def test_reload_preemption_trace(tmp_path, params, mesh1):
    """Hot reload mid-stream: the in-flight request's trace reads
    preempted{reason=reload} → re-admitted (fresh slot, requeued at
    the front) → finished."""
    from deeplearning4j_tpu.util.checkpointing import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "w"), use_orbax=False)
    mgr.save_tree(params, 1)
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(max_new_tokens=10))
    h = eng.submit(_prompt())
    eng.tick()
    assert eng.reload_weights(mgr, step=1) == 1
    eng.run_pending()
    assert h.status == RequestStatus.COMPLETED
    kinds = h.trace.kinds()
    i_pre = kinds.index("preempted")
    assert h.trace.events[i_pre].data["reason"] == "reload"
    assert "admitted" in kinds[i_pre:]
    assert kinds[-1] == "finished"


# ---------------------------------------------------------------------------
# SLO tracker: exact values under an injected clock
# ---------------------------------------------------------------------------

def test_slo_tracker_deterministic_values():
    """TTFT / TPOT / e2e / queue-age / goodput computed from traces
    with controlled timestamps — the definitions, verified exactly."""
    clk = {"t": 0.0}
    rec = FlightRecorder(clock=lambda: clk["t"])
    reg = MetricsRegistry()
    slo = SLOTracker(registry=reg, window=8)

    tr = rec.start_trace(1)
    tr.add("submit")                       # t=0
    tr.add("queued")
    clk["t"] = 1.0
    tr.add("admitted", slot=0, bucket=16)
    slo.admitted(tr)                       # queue age = 1.0
    clk["t"] = 1.25
    ev = tr.add("prefill_done", tokens=1)
    slo.first_token(tr, ev.ts)             # ttft = 1.25
    clk["t"] = 2.75
    tr.add("decode_chunk", tokens=3)       # 4 tokens over 1.5s
    clk["t"] = 3.0
    tr.add("finished", tokens=4, partial=False)
    slo.finished(tr)                       # e2e = 3.0, tpot = 0.5

    tr2 = rec.start_trace(2)
    tr2.add("submit")
    clk["t"] = 3.5
    tr2.add("shed", reason="deadline")
    slo.finished(tr2)

    rep = slo.report()
    assert rep["window"] == 2
    assert rep["goodput"] == 0.5 and slo.goodput() == 0.5
    assert rep["ttft_p50_ms"] == 1250.0
    assert rep["tpot_p50_ms"] == 500.0
    assert rep["queue_age_p50_ms"] == 1000.0
    # e2e values: 3.0 (trace 1) and 0.5 (trace 2, submit 3.0→shed 3.5)
    assert rep["e2e_p50_ms"] == 500.0      # nearest-rank: lower of 2
    assert rep["e2e_p99_ms"] == 3000.0
    assert rep["outcomes"]["shed"] == 1

    # the same numbers landed in the registry histograms
    assert reg.get("serving_ttft_seconds")._unlabeled().snapshot() \
        [1] == pytest.approx(1.25)
    assert reg.get("serving_tpot_seconds")._unlabeled().snapshot() \
        [1] == pytest.approx(0.5)
    assert reg.get("serving_queue_age_seconds")._unlabeled() \
        .snapshot()[2] == 1
    assert reg.get("serving_slo_requests").labels("ok").value == 1
    assert reg.get("serving_slo_requests").labels("shed").value == 1
    assert reg.get("serving_goodput_ratio").value == 0.5

    text = prometheus_text(reg)
    assert "serving_ttft_seconds_bucket" in text
    assert "serving_goodput_ratio 0.5" in text


def test_slo_queue_age_counts_reinsertion_wait():
    """A preempted request's second wait (preempted → re-admitted) is
    a real queue wait: admitted() measures from the LAST preemption,
    not from submit."""
    clk = {"t": 0.0}
    rec = FlightRecorder(clock=lambda: clk["t"])
    reg = MetricsRegistry()
    slo = SLOTracker(registry=reg)
    tr = rec.start_trace(1)
    tr.add("submit")
    clk["t"] = 1.0
    tr.add("admitted", slot=0)
    slo.admitted(tr)                       # wait 1.0
    clk["t"] = 5.0
    tr.add("preempted", reason="reload")
    clk["t"] = 5.25
    tr.add("admitted", slot=1)
    slo.admitted(tr)                       # wait 0.25, NOT 5.25
    cum, total, count = reg.get(
        "serving_queue_age_seconds")._unlabeled().snapshot()
    assert count == 2 and total == pytest.approx(1.25)


# ---------------------------------------------------------------------------
# timeline export
# ---------------------------------------------------------------------------

def test_timeline_is_valid_trace_event_json(params, mesh1):
    """eng.timeline() round-trips through JSON and carries the
    Chrome/Perfetto trace_event structure: thread_name metadata naming
    ONE LANE PER SLOT plus the queue lane, complete ('X') spans with
    non-negative durations, and instant decode events."""
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(max_batch_size=4))
    hs = [eng.submit(_prompt(8, i)) for i in range(3)]
    eng.run_pending()
    assert all(h.done() for h in hs)

    tl = json.loads(json.dumps(eng.timeline()))
    assert tl["displayTimeUnit"] == "ms"
    evs = tl["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert {"name", "ph", "pid", "tid"} <= set(e)

    lanes = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "queue" in lanes
    assert {f"slot {i}" for i in range(eng._num_slots)} <= lanes

    xs = [e for e in evs if e["ph"] == "X"]
    assert xs and all(e["dur"] >= 0 and e["ts"] >= 0 for e in xs)
    # every request shows a queue wait AND a slot residency
    for h in hs:
        mine = [e for e in xs if e["args"].get("rid") == h.rid]
        assert any(e["tid"] == 0 for e in mine)       # queue lane
        assert any(e["tid"] >= 1 for e in mine)       # a slot lane
    assert any(e["ph"] == "i" and e["name"].startswith("decode_chunk")
               for e in evs)

    # standalone export over raw events agrees
    tl2 = timeline_json(eng.recorder, num_slots=eng._num_slots)
    assert len(tl2["traceEvents"]) == len(evs)


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------

def test_debugz_slo_timeline_endpoints(params, mesh1):
    """MetricsServer(debug=, slo=, timeline=) serves the three
    introspection endpoints; a server without them 404s."""
    eng = InferenceEngine(CFG, mesh1, params, _config())
    hs = [eng.submit(_prompt(8, i)) for i in range(2)]
    eng.run_pending()
    srv = MetricsServer(eng.registry, port=0, health=eng.health,
                        ready=eng.ready, debug=eng.debugz,
                        slo=eng.slo_report, timeline=eng.timeline)
    try:
        code, body = _get(srv.url + "/debugz")
        dbg = json.loads(body)
        assert code == 200
        assert dbg["mode"] == "continuous" and dbg["slots"] == []
        assert dbg["queue_depth"] == 0 and dbg["breaker"] == "closed"
        kinds = [e["kind"] for e in dbg["recent_events"]]
        assert kinds.count("finished") == 2
        assert {e["rid"] for e in dbg["recent_events"]} == \
            {h.rid for h in hs}

        code, body = _get(srv.url + "/slo")
        rep = json.loads(body)
        assert code == 200 and rep["window"] == 2
        assert rep["goodput"] == 1.0 and rep["ttft_p50_ms"] > 0

        code, body = _get(srv.url + "/timeline.json")
        assert code == 200
        assert json.loads(body)["traceEvents"]

        code, text = _get(srv.url + "/metrics")   # still a scraper
        assert code == 200 and "serving_ttft_seconds_bucket" in text
    finally:
        srv.stop()

    bare = MetricsServer(eng.registry, port=0)
    try:
        for path in ("/debugz", "/slo", "/timeline.json"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(bare.url + path)
            assert e.value.code == 404
    finally:
        bare.stop()


def test_debugz_shows_live_slots_and_queue(params, mesh1):
    """Mid-flight debugz: seated request in the slot table with its
    progress, waiting request in the queue with an age."""
    eng = InferenceEngine(CFG, mesh1, params,
                          _config(max_batch_size=1, max_new_tokens=10))
    seated = eng.submit(_prompt(8, 1))
    waiting = eng.submit(_prompt(8, 2))
    for _ in range(4):    # seat 1 (pool of 1), ~1 chunk committed
        eng.tick()        # (the pipelined default commits a tick late)
        dbg = eng.debugz()
        if dbg["slots"] and dbg["slots"][0]["generated"] > 0:
            break
    assert [s["rid"] for s in dbg["slots"]] == [seated.rid]
    assert dbg["slots"][0]["status"] == "running"
    assert 0 < dbg["slots"][0]["generated"] < 10
    assert dbg["slots"][0]["age_s"] > 0
    assert [q["rid"] for q in dbg["queue"]] == [waiting.rid]
    assert dbg["queue"][0]["queue_age_s"] > 0
    eng.run_pending()


# ---------------------------------------------------------------------------
# disable-by-injection + ring bounds
# ---------------------------------------------------------------------------

def test_null_recorder_disabled_by_injection(params, mesh1):
    """registry=NULL_REGISTRY (or recorder=NULL_RECORDER) turns every
    trace/SLO call into a no-op — and decode results are identical to
    the recorded engine's."""
    from deeplearning4j_tpu.observability import NULL_REGISTRY
    eng_off = InferenceEngine(CFG, mesh1, params, _config(),
                              registry=NULL_REGISTRY)
    assert eng_off.recorder is NULL_RECORDER
    h = eng_off.submit(_prompt())
    assert h.trace is NULL_TRACE
    eng_off.run_pending()
    assert h.trace.kinds() == [] and len(eng_off.recorder) == 0
    assert eng_off.slo_report() == {}
    dbg = eng_off.debugz()                 # still answers, no events
    assert dbg["recent_events"] == [] and dbg["queue_depth"] == 0

    eng_on = InferenceEngine(CFG, mesh1, params, _config())
    h_on = eng_on.submit(_prompt())
    eng_on.run_pending()
    np.testing.assert_array_equal(h.result(0), h_on.result(0))

    # explicit recorder injection beats the registry default
    eng_mix = InferenceEngine(CFG, mesh1, params, _config(),
                              recorder=NULL_RECORDER)
    hm = eng_mix.submit(_prompt())
    eng_mix.run_pending()
    assert hm.trace is NULL_TRACE and eng_mix.slo_report() == {}


def test_recorder_ring_bounded_and_typed():
    rec = FlightRecorder(capacity=4)
    tr = rec.start_trace(7)
    for _ in range(3):
        tr.add("submit")
        tr.add("queued")
    assert len(rec) == 4                   # ring dropped the oldest
    assert len(tr) == 6                    # the trace kept its own
    assert [e.kind for e in rec.recent(2)] == ["submit", "queued"]
    assert all(e.rid == 7 for e in rec.recent())
    with pytest.raises(ValueError, match="unknown event kind"):
        tr.add("exploded")
    with pytest.raises(ValueError, match="unknown event kind"):
        rec.record("exploded")
