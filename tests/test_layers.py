"""Layer forward shapes and values (reference test analog:
deeplearning4j-core/src/test/java/org/deeplearning4j/nn/layers/**)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (ActivationLayer,
                                          BatchNormalization,
                                          ConvolutionLayer, DenseLayer,
                                          DropoutLayer, EmbeddingLayer,
                                          GlobalPoolingLayer,
                                          GravesBidirectionalLSTM,
                                          GravesLSTM, LossLayer,
                                          OutputLayer, SubsamplingLayer,
                                          ZeroPaddingLayer)
from deeplearning4j_tpu.nn.layers.normalization import (
    LocalResponseNormalization)

KEY = jax.random.PRNGKey(0)


def test_dense_forward():
    layer = DenseLayer(n_in=4, n_out=3, activation="identity",
                       weight_init="xavier")
    p = layer.init_params(KEY)
    x = jnp.ones((2, 4))
    y, _ = layer.apply(p, {}, x)
    assert y.shape == (2, 3)
    np.testing.assert_allclose(y, x @ p["W"] + p["b"], rtol=1e-6)


def test_dense_on_sequence():
    layer = DenseLayer(n_in=4, n_out=3, activation="relu")
    p = layer.init_params(KEY)
    y, _ = layer.apply(p, {}, jnp.ones((2, 7, 4)))
    assert y.shape == (2, 7, 3)


def test_conv_shapes():
    layer = ConvolutionLayer(n_in=1, n_out=8, kernel_size=(5, 5),
                             activation="relu")
    out_t = layer.update_input_type(InputType.convolutional(28, 28, 1))
    assert (out_t.height, out_t.width, out_t.channels) == (24, 24, 8)
    p = layer.init_params(KEY)
    assert p["W"].shape == (5, 5, 1, 8)
    y, _ = layer.apply(p, {}, jnp.ones((2, 28, 28, 1)))
    assert y.shape == (2, 24, 24, 8)


def test_conv_same_mode():
    layer = ConvolutionLayer(n_in=3, n_out=4, kernel_size=(3, 3),
                             convolution_mode="same", stride=(1, 1))
    out_t = layer.update_input_type(InputType.convolutional(8, 8, 3))
    assert (out_t.height, out_t.width) == (8, 8)
    p = layer.init_params(KEY)
    y, _ = layer.apply(p, {}, jnp.ones((1, 8, 8, 3)))
    assert y.shape == (1, 8, 8, 4)


def test_subsampling_max_and_avg():
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    mx = SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                          stride=(2, 2))
    y, _ = mx.apply({}, {}, x)
    np.testing.assert_allclose(np.asarray(y)[0, :, :, 0],
                               [[5, 7], [13, 15]])
    av = SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2),
                          stride=(2, 2))
    y, _ = av.apply({}, {}, x)
    np.testing.assert_allclose(np.asarray(y)[0, :, :, 0],
                               [[2.5, 4.5], [10.5, 12.5]])


def test_batchnorm_train_and_inference():
    layer = BatchNormalization()
    layer.update_input_type(InputType.feed_forward(5))
    p = layer.init_params(KEY)
    s = layer.init_state()
    x = jax.random.normal(KEY, (64, 5)) * 3 + 1
    y, s2 = layer.apply(p, s, x, train=True)
    # normalized batch: ~0 mean, ~1 var
    np.testing.assert_allclose(np.asarray(y).mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(y).std(0), 1.0, atol=1e-2)
    # running stats moved toward batch stats
    assert not np.allclose(np.asarray(s2["mean"]), 0.0)
    # inference path uses running stats
    y2, s3 = layer.apply(p, s2, x, train=False)
    assert s3 is s2 or np.allclose(np.asarray(s3["mean"]),
                                   np.asarray(s2["mean"]))


def test_lrn_shape():
    layer = LocalResponseNormalization()
    x = jax.random.normal(KEY, (2, 4, 4, 8))
    y, _ = layer.apply({}, {}, x)
    assert y.shape == x.shape
    assert float(jnp.max(jnp.abs(y))) <= float(jnp.max(jnp.abs(x)))


def test_zero_padding():
    layer = ZeroPaddingLayer(padding=(1, 2))
    y, _ = layer.apply({}, {}, jnp.ones((1, 4, 4, 2)))
    assert y.shape == (1, 6, 8, 2)


def test_embedding_lookup_matches_onehot():
    layer = EmbeddingLayer(n_in=7, n_out=3, activation="identity")
    p = layer.init_params(KEY)
    idx = jnp.array([0, 3, 6])
    y_idx, _ = layer.apply(p, {}, idx)
    onehot = jax.nn.one_hot(idx, 7)
    y_oh, _ = layer.apply(p, {}, onehot)
    np.testing.assert_allclose(np.asarray(y_idx), np.asarray(y_oh),
                               rtol=1e-5)


def test_lstm_shapes_and_state():
    layer = GravesLSTM(n_in=6, n_out=4, activation="tanh")
    layer.update_input_type(InputType.recurrent(6, 10))
    p = layer.init_params(KEY)
    assert p["W"].shape == (6, 16)
    assert p["RW"].shape == (4, 16)
    assert "pI" in p  # peepholes present (Graves)
    x = jax.random.normal(KEY, (3, 10, 6))
    y, _ = layer.apply(p, {}, x)
    assert y.shape == (3, 10, 4)
    # step-by-step equals full scan
    carry = layer.initial_carry(3, jnp.float32)
    outs = []
    for t in range(10):
        carry, h = layer.step(p, carry, x[:, t])
        outs.append(h)
    np.testing.assert_allclose(np.asarray(jnp.stack(outs, 1)),
                               np.asarray(y), rtol=1e-4, atol=1e-5)


def test_lstm_masking_freezes_state():
    layer = GravesLSTM(n_in=3, n_out=2)
    p = layer.init_params(KEY)
    x = jax.random.normal(KEY, (2, 5, 3))
    mask = jnp.array([[1, 1, 1, 1, 1], [1, 1, 0, 0, 0]], jnp.float32)
    y, _ = layer.apply(p, {}, x, mask=mask)
    # masked outputs are zero
    np.testing.assert_allclose(np.asarray(y[1, 2:]), 0.0, atol=1e-7)


def test_bidirectional_lstm():
    layer = GravesBidirectionalLSTM(n_in=3, n_out=4, mode="add")
    p = layer.init_params(KEY)
    x = jax.random.normal(KEY, (2, 6, 3))
    y, _ = layer.apply(p, {}, x)
    assert y.shape == (2, 6, 4)
    concat = GravesBidirectionalLSTM(n_in=3, n_out=4, mode="concat")
    pc = concat.init_params(KEY)
    y2, _ = concat.apply(pc, {}, x)
    assert y2.shape == (2, 6, 8)


def test_global_pooling_masked():
    layer = GlobalPoolingLayer(pooling_type="avg")
    x = jnp.stack([jnp.ones((4, 3)), 2 * jnp.ones((4, 3))])  # [2, 4, 3]
    mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
    y, _ = layer.apply({}, {}, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y), [[1, 1, 1], [2, 2, 2]],
                               rtol=1e-6)


def test_dropout_train_vs_inference():
    layer = DropoutLayer(rate=0.5)
    x = jnp.ones((8, 100))
    y_inf, _ = layer.apply({}, {}, x, train=False, key=KEY)
    np.testing.assert_allclose(np.asarray(y_inf), 1.0)
    y_tr, _ = layer.apply({}, {}, x, train=True, key=KEY)
    arr = np.asarray(y_tr)
    assert ((arr == 0) | (np.isclose(arr, 2.0))).all()
    assert 0.3 < (arr == 0).mean() < 0.7


def test_output_layer_loss_decreasing_direction():
    layer = OutputLayer(n_in=4, n_out=3, activation="softmax",
                        loss_function="mcxent")
    p = layer.init_params(KEY)
    x = jax.random.normal(KEY, (5, 4))
    y = jax.nn.one_hot(jnp.array([0, 1, 2, 0, 1]), 3)
    loss = layer.loss(p, x, y)
    assert loss.shape == ()
    assert float(loss) > 0
