"""UI / stats pipeline tests.

Models the reference's UI test strategy (SURVEY.md §4: SBE encode/decode
round-trip TestStatsClasses; storage backends TestStatsStorage; Play
server smoke TestPlayUI). JSON records replace SBE, so the round-trip
test becomes storage round-trip; the server smoke test runs against the
real HTTP server on an ephemeral port.
"""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ui import (FileStatsStorage, InMemoryStatsStorage,
                                   Persistable, RemoteUIStatsStorageRouter,
                                   SqliteStatsStorage, StatsListener,
                                   UIServer)


def _record(sid="s1", tid="Update", wid="w0", ts=1.0, **extra):
    return Persistable({"session_id": sid, "type_id": tid,
                        "worker_id": wid, "timestamp": ts, **extra})


@pytest.mark.parametrize("make_storage", [
    lambda tmp: InMemoryStatsStorage(),
    lambda tmp: FileStatsStorage(str(tmp / "stats.jsonl")),
    lambda tmp: SqliteStatsStorage(str(tmp / "stats.db")),
], ids=["memory", "file", "sqlite"])
def test_storage_backends_roundtrip(make_storage, tmp_path):
    st = make_storage(tmp_path)
    st.put_static_info(_record(tid="StaticInfo", info={"a": 1}))
    st.put_update(_record(ts=1.0, iteration=0, score=2.0))
    st.put_update(_record(ts=2.0, iteration=1, score=1.5))
    assert st.list_session_ids() == ["s1"]
    assert "Update" in st.list_type_ids_for_session("s1")
    assert st.list_worker_ids_for_session("s1") == ["w0"]
    ups = st.get_all_updates_after("s1", "Update", "w0", -1)
    assert [u["score"] for u in ups] == [2.0, 1.5]
    assert st.get_all_updates_after("s1", "Update", "w0", 1.5)[0][
        "iteration"] == 1
    static = st.get_static_info("s1", "StaticInfo", "w0")
    assert static["info"] == {"a": 1}
    latest = st.get_latest_update("s1", "Update", "w0")
    assert latest["score"] == 1.5
    st.close()


def test_file_storage_persists_across_reopen(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    st = FileStatsStorage(p)
    st.put_update(_record(score=3.0))
    st.close()
    st2 = FileStatsStorage(p)
    assert st2.get_latest_update("s1", "Update", "w0")["score"] == 3.0
    st2.close()


def test_stats_listener_collects_norms_and_histograms():
    """StatsListener on a real training run (reference:
    BaseStatsListener.iterationDone:287)."""
    from deeplearning4j_tpu.nn.conf.configuration import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    storage = InMemoryStatsStorage()
    listener = StatsListener(storage, frequency=1, session_id="test_sess")
    conf = NeuralNetConfiguration(seed=1, learning_rate=0.1).list(
        DenseLayer(n_in=4, n_out=8, activation="relu"),
        OutputLayer(n_out=3, activation="softmax", loss_function="mcxent"))
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(listener)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    for _ in range(3):
        net.fit(x, y)

    assert storage.list_session_ids() == ["test_sess"]
    static = storage.get_static_info("test_sess", "StaticInfo", "worker_0")
    assert static["model"]["num_params"] > 0
    ups = storage.get_all_updates_after("test_sess", "Update", "worker_0",
                                        -1)
    assert len(ups) == 3
    u = ups[-1]
    assert np.isfinite(u["score"])
    # per-parameter stats present with histograms
    pkeys = list(u["parameters"])
    assert any("W" in k for k in pkeys)
    first = u["parameters"][pkeys[0]]
    assert {"mean", "std", "min", "max", "norm", "histogram"} <= set(first)
    assert len(first["histogram"]) == 20


def test_ui_server_endpoints_and_remote_router():
    server = UIServer(port=0)  # ephemeral
    try:
        storage = InMemoryStatsStorage()
        server.attach(storage)
        storage.put_static_info(_record(tid="StaticInfo",
                                        hardware={"x": 1}))
        storage.put_update(_record(ts=1.0, iteration=0, score=2.5,
                                   parameters={"l/W": {"norm": 1.0}}))

        def get(path):
            with urllib.request.urlopen(server.url + path, timeout=5) as r:
                return json.loads(r.read())

        assert get("/train/sessions") == ["s1"]
        ov = get("/train/overview?sid=s1")
        assert ov["scores"] == [2.5]
        model = get("/train/model?sid=s1")
        assert model["l/W"]["norm"] == 1.0
        sysinfo = get("/train/system?sid=s1")
        assert sysinfo["hardware"] == {"x": 1}
        # dashboard HTML served
        with urllib.request.urlopen(server.url + "/", timeout=5) as r:
            assert b"Training dashboard" in r.read()

        # remote router → server (reference: RemoteUIStatsStorageRouter →
        # remote receiver endpoint)
        router = RemoteUIStatsStorageRouter(server.url)
        router.put_update(_record(sid="remote_sess", ts=1.0, iteration=0,
                                  score=9.9))
        assert "remote_sess" in get("/train/sessions")
        ov2 = get("/train/overview?sid=remote_sess")
        assert ov2["scores"] == [9.9]
    finally:
        server.stop()


def test_ui_server_tsne_activations_flow_modules(tmp_path):
    """The reference Play UI's extra modules (TsneModule,
    ActivationsModule, FlowModule) — viewer routes over listener
    artifacts."""
    server = UIServer(port=0)
    try:
        def get(path):
            with urllib.request.urlopen(server.url + path, timeout=5) as r:
                return json.loads(r.read())

        # t-SNE: upload via API, read back via route; page served
        server.upload_tsne([[0.0, 1.0], [2.0, 3.0]], labels=["a", "b"])
        d = get("/tsne/coords")
        assert d["coords"] == [[0.0, 1.0], [2.0, 3.0]]
        assert d["labels"] == ["a", "b"]
        with urllib.request.urlopen(server.url + "/tsne", timeout=5) as r:
            assert b"t-SNE" in r.read()
        # also via HTTP POST (remote client)
        req = urllib.request.Request(
            server.url + "/tsne/upload",
            data=json.dumps({"coords": [[5, 6]]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read())["n"] == 1
        assert get("/tsne/coords")["coords"] == [[5, 6]]

        # activations: serve ConvolutionalIterationListener .npy grids
        grid = np.arange(12, dtype=np.float32).reshape(3, 2, 2)
        np.save(tmp_path / "iter0_layer_0.npy", grid)
        server.attach_activations_dir(tmp_path)
        assert get("/activations")["grids"] == ["iter0_layer_0.npy"]
        got = get("/activations?name=iter0_layer_0.npy")
        np.testing.assert_array_equal(np.asarray(got["grid"]), grid)
        with pytest.raises(urllib.error.HTTPError):
            get("/activations?name=../etc/passwd")

        # flow: serve FlowIterationListener JSON
        flow = {"iteration": 3, "score": 1.5,
                "layers": [{"name": "l0", "type": "DenseLayer",
                            "inputs": []}]}
        (tmp_path / "flow.json").write_text(json.dumps(flow))
        server.attach_flow(tmp_path / "flow.json")
        assert get("/flow") == flow
    finally:
        server.stop()
